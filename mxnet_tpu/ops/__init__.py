"""Operator library: one registry, pure JAX implementations.

Importing this package registers all ops (analog of the reference's static
registration at library load; src/operator/*.cc NNVM_REGISTER_OP blocks).
"""
from . import registry
from .registry import register, get, list_ops, alias, Operator

from . import elemwise      # noqa: F401
from . import reduce        # noqa: F401
from . import matrix        # noqa: F401
from . import nn            # noqa: F401
from . import random_ops    # noqa: F401
from . import init_ops      # noqa: F401
from . import optimizer_ops # noqa: F401
from . import image_ops     # noqa: F401
from . import quantization  # noqa: F401
from . import quant_serve   # noqa: F401
from . import contrib_ops   # noqa: F401
from . import custom_op     # noqa: F401
from . import vision_ops    # noqa: F401
from . import pallas_flash  # noqa: F401
from ..kernels import bn_act as _kernel_bn_act    # noqa: F401  (tier ops)
from ..kernels import mlp as _kernel_mlp          # noqa: F401
from . import linalg        # noqa: F401
from . import legacy_aliases  # noqa: F401  (must come after the bases)
