"""Random sampling operators.

Parity: src/operator/random/sample_op.cc + multisample/multinomial/shuffle.
Design: stateful facade over stateless JAX PRNG (see mxnet_tpu/random.py and
SURVEY.md §7 hard-part 5). Every op draws a key via random.next_key() — global
chain in eager mode, threaded key input inside traced graphs.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register, alias
from .. import random as _random
from ..base import normalize_dtype, index_dtype as _index_dtype


def _dt(dtype):
    return normalize_dtype(dtype or "float32")


@register("_random_uniform", is_random=True)
def random_uniform(*, low=0.0, high=1.0, shape=(1,), dtype="float32", ctx=None):
    return jax.random.uniform(_random.next_key(), tuple(shape), _dt(dtype),
                              low, high)


@register("_random_normal", is_random=True)
def random_normal(*, loc=0.0, scale=1.0, shape=(1,), dtype="float32", ctx=None):
    return loc + scale * jax.random.normal(_random.next_key(), tuple(shape),
                                           _dt(dtype))


alias("_random_normal", "_random_gaussian")


@register("_random_uniform_like", is_random=True)
def random_uniform_like(data, *, low=0.0, high=1.0, dtype=None, ctx=None):
    """Draw uniform samples shaped like ``data`` (reference
    sample_op.cc `_random_uniform_like`)."""
    return jax.random.uniform(_random.next_key(), data.shape,
                              _dt(dtype) if dtype else data.dtype, low, high)


@register("_random_normal_like", is_random=True)
def random_normal_like(data, *, loc=0.0, scale=1.0, dtype=None, ctx=None):
    """Draw normal samples shaped like ``data`` (reference
    sample_op.cc `_random_normal_like`)."""
    return loc + scale * jax.random.normal(
        _random.next_key(), data.shape,
        _dt(dtype) if dtype else data.dtype)


@register("_random_gamma", is_random=True)
def random_gamma(*, alpha=1.0, beta=1.0, shape=(1,), dtype="float32", ctx=None):
    return beta * jax.random.gamma(_random.next_key(), alpha, tuple(shape),
                                   _dt(dtype))


@register("_random_exponential", is_random=True)
def random_exponential(*, lam=1.0, shape=(1,), dtype="float32", ctx=None):
    return jax.random.exponential(_random.next_key(), tuple(shape),
                                  _dt(dtype)) / lam


@register("_random_poisson", is_random=True)
def random_poisson(*, lam=1.0, shape=(1,), dtype="float32", ctx=None):
    return jax.random.poisson(_random.next_key(), lam, tuple(shape)).astype(_dt(dtype))


@register("_random_negative_binomial", is_random=True)
def random_negbinomial(*, k=1, p=1.0, shape=(1,), dtype="float32", ctx=None):
    key1, key2 = jax.random.split(_random.next_key())
    lam = jax.random.gamma(key1, k, tuple(shape)) * (1 - p) / p
    return jax.random.poisson(key2, lam, tuple(shape)).astype(_dt(dtype))


@register("_random_generalized_negative_binomial", is_random=True)
def random_gen_negbinomial(*, mu=1.0, alpha=1.0, shape=(1,), dtype="float32", ctx=None):
    key1, key2 = jax.random.split(_random.next_key())
    r = 1.0 / alpha
    p = r / (r + mu)
    lam = jax.random.gamma(key1, r, tuple(shape)) * (1 - p) / p
    return jax.random.poisson(key2, lam, tuple(shape)).astype(_dt(dtype))


@register("_random_randint", is_random=True)
def random_randint(*, low=0, high=1, shape=(1,), dtype="int32", ctx=None):
    return jax.random.randint(_random.next_key(), tuple(shape), low, high,
                              _dt(dtype))


# sample_* variants: per-element distribution parameters as inputs
@register("_sample_uniform", is_random=True)
def sample_uniform(low, high, *, shape=(), dtype="float32"):
    out_shape = low.shape + tuple(shape)
    u = jax.random.uniform(_random.next_key(), out_shape, _dt(dtype))
    ext = (...,) + (None,) * len(tuple(shape))
    return low[ext] + u * (high - low)[ext]


@register("_sample_normal", is_random=True)
def sample_normal(mu, sigma, *, shape=(), dtype="float32"):
    out_shape = mu.shape + tuple(shape)
    z = jax.random.normal(_random.next_key(), out_shape, _dt(dtype))
    ext = (...,) + (None,) * len(tuple(shape))
    return mu[ext] + z * sigma[ext]


@register("_sample_gamma", is_random=True)
def sample_gamma(alpha, beta, *, shape=(), dtype="float32"):
    out_shape = alpha.shape + tuple(shape)
    ext = (...,) + (None,) * len(tuple(shape))
    g = jax.random.gamma(_random.next_key(),
                         jnp.broadcast_to(alpha[ext], out_shape), dtype=_dt(dtype))
    return g * beta[ext]


@register("_sample_multinomial", is_random=True,
          num_outputs=lambda p: 2 if p.get("get_prob") else 1)
def sample_multinomial(data, *, shape=(), get_prob=False, dtype="int32"):
    # data: (..., K) probabilities
    n = 1
    for s in tuple(shape) or ():
        n *= s
    logits = jnp.log(jnp.maximum(data, 1e-30))
    flat = logits.reshape(-1, logits.shape[-1])
    keys = jax.random.split(_random.next_key(), flat.shape[0])
    draws = jax.vmap(lambda k, lg: jax.random.categorical(k, lg, shape=(max(n, 1),)))(keys, flat)
    out_shape = data.shape[:-1] + tuple(shape) if shape else data.shape[:-1]
    out = draws.reshape(out_shape).astype(_dt(dtype))
    if get_prob:
        lp = jnp.take_along_axis(
            jax.nn.log_softmax(flat, -1), draws, axis=-1).reshape(out_shape)
        return out, lp
    return out


@register("_shuffle", is_random=True)
def shuffle(data):
    return jax.random.permutation(_random.next_key(), data, axis=0)


@register("_sample_unique_zipfian", is_random=True, num_outputs=2)
def sample_unique_zipfian(*, range_max, shape=(1, 1)):
    """Sampling WITHOUT replacement from the log-uniform (zipfian)
    proposal distribution, plus the number of tries it took — the
    sampled-softmax helper (reference
    src/operator/random/unique_sample_op.h:109-136 rejection loop).
    TPU form: a vmapped ``lax.while_loop`` drawing a vectorized block of
    proposals per iteration, deduped by stable sort and checked against
    an O(n) sorted-set carry — identical semantics (exact uniques, exact
    try counts per row: draws past the filling one "never happened"),
    nothing scaling with range_max, no host-side set.
    """
    shape = tuple(shape)
    if len(shape) == 1:
        shape = (1,) + shape
    batch, n = shape
    if n > range_max:
        raise ValueError(
            "Number of samples (%d) cannot exceed the number of possible "
            "classes (%d)" % (n, range_max))
    log_rm = jnp.float32(jnp.log(float(range_max)))
    idt = _index_dtype()
    # proposals per while_loop iteration: enough that the loop usually
    # finishes in a handful of vectorized rounds instead of one device
    # round-trip per draw
    blk = min(max(64, 2 * n), 8192)

    sentinel = jnp.asarray(range_max, idt)   # > every valid sample

    def one_row(key):
        # carry: (count, tries, buf insertion-ordered, sset sorted+padded,
        # key) — O(n) state per row; membership is a searchsorted against
        # sset, in-block dedup a stable sort, so nothing scales with
        # range_max or blk^2
        def cond(st):
            return st[0] < n

        def body(st):
            count, tries, buf, sset, key = st
            key, sub = jax.random.split(key)
            x = jax.random.uniform(sub, (blk,))
            vals = jnp.clip(
                jnp.round(jnp.exp(x * log_rm)).astype(idt) - 1,
                0, range_max - 1)
            # first DRAWN occurrence within the block: stable sort groups
            # equal values with original draw order preserved, the head of
            # each run is the first occurrence
            order = jnp.argsort(vals, stable=True)
            svals = vals[order]
            head = jnp.concatenate([jnp.ones((1,), jnp.bool_),
                                    svals[1:] != svals[:-1]])
            first_occ = jnp.zeros((blk,), jnp.bool_).at[order].set(head)
            in_prior = sset[
                jnp.clip(jnp.searchsorted(sset, vals), 0, n - 1)] == vals
            is_new = first_occ & ~in_prior
            # set size after each draw if applied in order; the loop
            # "stops" at the draw that fills the set — later proposals
            # were never drawn in the reference's sequential semantics
            pos = count + jnp.cumsum(is_new.astype(jnp.int32))
            apply = is_new & (pos <= n)
            slot = jnp.where(apply, pos - 1, n)     # n = OOB -> dropped
            buf = buf.at[slot].set(vals, mode="drop")
            merged = jnp.concatenate(
                [sset, jnp.where(apply, vals, sentinel)])
            sset = jnp.sort(merged)[:n]
            filled = pos[-1] >= n
            # index of the filling draw (argmax finds the first True)
            t_fill = jnp.argmax(pos >= n)
            tries = tries + jnp.where(filled, t_fill + 1, blk)
            return (jnp.minimum(pos[-1], n), tries, buf, sset, key)

        init = (jnp.int32(0), jnp.int32(0), jnp.zeros((n,), idt),
                jnp.full((n,), sentinel, idt), key)
        count, tries, buf, _, _ = jax.lax.while_loop(cond, body, init)
        return buf, tries.astype(idt)

    keys = jax.random.split(_random.next_key(), batch)
    samples, tries = jax.vmap(one_row)(keys)
    return samples, tries
