"""Pallas TPU flash-attention kernel.

The hot op of long-context training, hand-tiled for the MXU per
/opt/skills/guides/pallas_guide.md: the Q block lives in VMEM, the kernel
streams KV blocks with an online softmax (f32 running max / denominator /
accumulator in VMEM scratch), and the QK^T / PV matmuls run on the MXU
with ``preferred_element_type=f32``.  Grid = (batch*heads, q_blocks); the
KV stream is a ``fori_loop`` inside the kernel so the accumulator never
leaves VMEM.  Causal masking prunes the loop bound (blocks entirely in
the future are never read).

Backward: ``jax.custom_vjp`` whose bwd recomputes with the pure-jax
blockwise (flash-pattern) attention and differentiates it — the standard
recompute-in-backward memory profile without a second hand-written
kernel.  (parallel/ring_attention.py holds that implementation; the
reference has no analog — its attention ops are cuDNN calls.)

On CPU the kernel runs in interpreter mode (tests); on TPU it lowers via
Mosaic.  ``mxnet_tpu.parallel.flash_attention`` auto-selects this kernel
on TPU when shapes allow.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_INF = -1e30


def _kernel(q_ref, k_ref, v_ref, o_ref, *, block_k, seq_k, causal,
            sm_scale, q_block, seq_q):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * sm_scale          # (bq, d)
    bq, d = q.shape

    if causal:
        # last kv position visible to this q block (global offsets align
        # the diagonals when seq_q != seq_k, as in blockwise_attention)
        q_hi = (qi + 1) * q_block - 1 + (seq_k - seq_q)
        n_blocks = jnp.minimum(q_hi // block_k + 1,
                               pl.cdiv(seq_k, block_k))
    else:
        n_blocks = pl.cdiv(seq_k, block_k)

    def body(j, carry):
        m, l, o = carry
        k_blk = k_ref[0, pl.ds(j * block_k, block_k), :]  # (bk, d)
        v_blk = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k_blk.astype(jnp.float32),
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)           # (bq, bk)
        kv_pos = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (bq, block_k), 1)
        mask = kv_pos < seq_k                              # tail padding
        if causal:
            q_pos = qi * q_block + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            mask &= kv_pos <= q_pos + (seq_k - seq_q)
        s = jnp.where(mask, s, _NEG_INF)
        m_blk = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m, m_blk)
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        p = jnp.where(mask, p, 0.0)
        l_new = l * corr + jnp.sum(p, axis=1, keepdims=True)
        o_new = o * corr + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, o_new

    m0 = jnp.full((bq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    o0 = jnp.zeros((bq, d), jnp.float32)
    m, l, o = jax.lax.fori_loop(0, n_blocks, body, (m0, l0, o0))
    o_ref[0] = (o / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_fwd(q, k, v, block_q, block_k, causal, interpret):
    b, h, tq, d = q.shape
    tk = k.shape[2]
    sm_scale = 1.0 / math.sqrt(d)
    block_q = min(block_q, tq)
    block_k = min(block_k, tk)

    pad_q = (-tq) % block_q
    pad_k = (-tk) % block_k
    qp = jnp.pad(q, ((0, 0), (0, 0), (0, pad_q), (0, 0))) if pad_q else q
    kp = jnp.pad(k, ((0, 0), (0, 0), (0, pad_k), (0, 0))) if pad_k else k
    vp = jnp.pad(v, ((0, 0), (0, 0), (0, pad_k), (0, 0))) if pad_k else v

    bh = b * h
    qp = qp.reshape(bh, tq + pad_q, d)
    kp = kp.reshape(bh, tk + pad_k, d)
    vp = vp.reshape(bh, tk + pad_k, d)
    n_q = (tq + pad_q) // block_q

    kernel = functools.partial(
        _kernel, block_k=block_k, seq_k=tk, causal=causal,
        sm_scale=sm_scale, q_block=block_q, seq_q=tq)
    out = pl.pallas_call(
        kernel,
        grid=(bh, n_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bi, qi: (bi, qi, 0)),
            pl.BlockSpec((1, tk + pad_k, d), lambda bi, qi: (bi, 0, 0)),
            pl.BlockSpec((1, tk + pad_k, d), lambda bi, qi: (bi, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bi, qi: (bi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, tq + pad_q, d), q.dtype),
        interpret=interpret,
    )(qp, kp, vp)
    out = out.reshape(b, h, tq + pad_q, d)
    return out[:, :, :tq] if pad_q else out


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, block_q=128, block_k=128, causal=False,
                    interpret=None):
    """Flash attention on (B, H, T, D) tensors via a pallas TPU kernel.

    ``interpret=None`` auto-selects: interpreter off TPU (tests), Mosaic
    on TPU. f32 accumulation regardless of input dtype.

    Fully-masked rows (causal with ``seq_q > seq_k``: queries before the
    first key) return **zeros** — the flash/blockwise convention shared
    with :func:`~mxnet_tpu.parallel.blockwise_attention`. The dense
    ``attention_reference`` instead softmaxes an all-masked row into a
    uniform distribution; that row is mathematically undefined, and the
    zero convention is what fused kernels produce.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash_fwd(q, k, v, block_q, block_k, causal, interpret)


def _fwd(q, k, v, block_q, block_k, causal, interpret):
    return flash_attention(q, k, v, block_q, block_k, causal,
                           interpret), (q, k, v)


def _bwd(block_q, block_k, causal, interpret, res, g):
    from ..parallel.ring_attention import blockwise_attention
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: blockwise_attention(
            q_, k_, v_, block_size=block_k, causal=causal), q, k, v)
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)


# eager/symbolic surface: mx.nd._contrib_FlashAttention(q, k, v, causal=...)
from .registry import register as _register  # noqa: E402


@_register("_contrib_FlashAttention")
def _contrib_flash_attention(q, k, v, *, causal=False, block_q=128,
                             block_k=128):
    """(B, H, T, D) flash attention as a registered op (pallas on TPU)."""
    return flash_attention(q, k, v, block_q, block_k, bool(causal))
