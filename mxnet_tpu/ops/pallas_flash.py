"""Pallas TPU flash-attention kernel.

The hot op of long-context training, hand-tiled for the MXU per
/opt/skills/guides/pallas_guide.md: the Q block lives in VMEM, the kernel
streams KV blocks with an online softmax (f32 running max / denominator /
accumulator in VMEM scratch), and the QK^T / PV matmuls run on the MXU
with ``preferred_element_type=f32``.  Grid = (batch*heads, q_blocks); the
KV stream is a ``fori_loop`` inside the kernel so the accumulator never
leaves VMEM.  Causal masking prunes the loop bound (blocks entirely in
the future are never read).

Backward: ``jax.custom_vjp`` whose bwd recomputes with the pure-jax
blockwise (flash-pattern) attention and differentiates it — the standard
recompute-in-backward memory profile without a second hand-written
kernel.  (parallel/ring_attention.py holds that implementation; the
reference has no analog — its attention ops are cuDNN calls.)

On CPU the kernel runs in interpreter mode (tests); on TPU it lowers via
Mosaic.  ``mxnet_tpu.parallel.flash_attention`` auto-selects this kernel
on TPU when shapes allow.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *,
            block_q, block_k, seq_q, seq_k, causal, sm_scale):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # causal: a KV block entirely in this Q block's future contributes
    # nothing — skip its compute (the diagonal offset seq_k - seq_q
    # aligns cross-length attention like blockwise_attention)
    if causal:
        visible = ki * block_k <= (qi + 1) * block_q - 1 + (seq_k - seq_q)
    else:
        visible = True

    @pl.when(visible)
    def _():
        q = q_ref[0].astype(jnp.float32) * sm_scale        # (bq, d)
        bq = q.shape[0]
        k_blk = k_ref[0]                                   # (bk, d)
        v_blk = v_ref[0]
        s = jax.lax.dot_general(
            q, k_blk.astype(jnp.float32),
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)            # (bq, bk)
        kv_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (bq, block_k), 1)
        mask = kv_pos < seq_k                              # tail padding
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            mask &= kv_pos <= q_pos + (seq_k - seq_q)
        s = jnp.where(mask, s, _NEG_INF)
        m = m_scr[:]
        l = l_scr[:]
        m_blk = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m, m_blk)
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        p = jnp.where(mask, p, 0.0)
        m_scr[:] = m_new
        l_scr[:] = l * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == n_k - 1)
    def _():
        o_ref[0] = (acc_scr[:]
                    / jnp.maximum(l_scr[:], 1e-30)).astype(o_ref.dtype)


def _flash_fwd(q, k, v, block_q, block_k, causal, interpret):
    b, h, tq, d = q.shape
    tk = k.shape[2]
    sm_scale = 1.0 / math.sqrt(d)
    block_q = min(block_q, tq)
    block_k = min(block_k, tk)

    pad_q = (-tq) % block_q
    pad_k = (-tk) % block_k
    qp = jnp.pad(q, ((0, 0), (0, 0), (0, pad_q), (0, 0))) if pad_q else q
    kp = jnp.pad(k, ((0, 0), (0, 0), (0, pad_k), (0, 0))) if pad_k else k
    vp = jnp.pad(v, ((0, 0), (0, 0), (0, pad_k), (0, 0))) if pad_k else v

    bh = b * h
    qp = qp.reshape(bh, tq + pad_q, d)
    kp = kp.reshape(bh, tk + pad_k, d)
    vp = vp.reshape(bh, tk + pad_k, d)
    n_q = (tq + pad_q) // block_q
    n_k = (tk + pad_k) // block_k

    # KV blocks are the innermost grid dim: each (block_k, d) tile is
    # DMA'd per step while the online-softmax state (m, l, acc) persists
    # in VMEM scratch — VMEM holds O(block) tiles, never the sequence, so
    # long contexts fit (the review of the first version found whole-KV
    # staging capped usable sequence length)
    kernel = functools.partial(
        _kernel, block_q=block_q, block_k=block_k, seq_q=tq, seq_k=tk,
        causal=causal, sm_scale=sm_scale)
    out = pl.pallas_call(
        kernel,
        grid=(bh, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bi, qi, ki: (bi, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda bi, qi, ki: (bi, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bi, qi, ki: (bi, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d),
                               lambda bi, qi, ki: (bi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, tq + pad_q, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(qp, kp, vp)
    out = out.reshape(b, h, tq + pad_q, d)
    return out[:, :, :tq] if pad_q else out


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, block_q=128, block_k=128, causal=False,
                    interpret=None):
    """Flash attention on (B, H, T, D) tensors via a pallas TPU kernel.

    ``interpret=None`` auto-selects: interpreter off TPU (tests), Mosaic
    on TPU. f32 accumulation regardless of input dtype.

    Fully-masked rows (causal with ``seq_q > seq_k``: queries before the
    first key) return **zeros** — the flash/blockwise convention shared
    with :func:`~mxnet_tpu.parallel.blockwise_attention`. The dense
    ``attention_reference`` instead softmaxes an all-masked row into a
    uniform distribution; that row is mathematically undefined, and the
    zero convention is what fused kernels produce.
    """
    if interpret is None:
        # any non-cpu platform is the accelerator (this environment's TPU
        # registers as 'axon' — equality with 'tpu' would silently run
        # the interpreter on the real chip; see context.py's idiom)
        interpret = jax.default_backend() == "cpu"
    return _flash_fwd(q, k, v, block_q, block_k, causal, interpret)


def _fwd(q, k, v, block_q, block_k, causal, interpret):
    return flash_attention(q, k, v, block_q, block_k, causal,
                           interpret), (q, k, v)


def _bwd(block_q, block_k, causal, interpret, res, g):
    from ..parallel.ring_attention import blockwise_attention
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: blockwise_attention(
            q_, k_, v_, block_size=block_k, causal=causal), q, k, v)
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)


# eager/symbolic surface: mx.nd._contrib_FlashAttention(q, k, v, causal=...)
from .registry import register as _register  # noqa: E402


@_register("_contrib_FlashAttention")
def _contrib_flash_attention(q, k, v, *, causal=False, block_q=128,
                             block_k=128):
    """(B, H, T, D) flash attention as a registered op (pallas on TPU).

    Tier-aware: under ``MXNET_KERNEL_TIER=safe|auto`` the call dispatches
    to the kernel-tier attention (kernels/attention.py — the
    ``mxk_flash_attn`` HLO name the bench census counts, tuning-cache
    tile configs, and a ``custom_vjp`` backward exact against the dense
    reference), so the gluon GPT's hybridized train step picks up the
    tuned kernel with zero model changes. With the tier off (the
    default) it lowers this module's kernel with the caller's explicit
    block sizes, unchanged — eligibility rejections (e.g. causal
    cross-length) take the same legacy path and the reason lands in
    ``tier.stats()['fallback']``."""
    from ..kernels import attention as _attn
    out = _attn.attend_or_none(q, k, v, causal=bool(causal))
    if out is not None:
        return out
    return flash_attention(q, k, v, block_q, block_k, bool(causal))
