"""Symbolic AlexNet builder (Krizhevsky et al. 2012), TPU-first.

Role parity: example/image-classification/symbols/alexnet.py in the
reference (the AlexNet rows of docs/faq/perf.md and the 256-GPU scaling
table). LRN is kept for architectural fidelity — XLA lowers it to a
windowed reduce; batch-norm-free, so the graph is pure conv/pool/fc.
"""
from __future__ import annotations

from .. import symbol as sym

__all__ = ["get_symbol", "alexnet"]


def get_symbol(num_classes=1000, dtype="float32", **kwargs):
    data = sym.Variable("data")
    c1 = sym.Convolution(data, kernel=(11, 11), stride=(4, 4), num_filter=96,
                         name="conv1")
    r1 = sym.Activation(c1, act_type="relu")
    l1 = sym.LRN(r1, alpha=1e-4, beta=0.75, knorm=2, nsize=5)
    p1 = sym.Pooling(l1, kernel=(3, 3), stride=(2, 2), pool_type="max")
    c2 = sym.Convolution(p1, kernel=(5, 5), pad=(2, 2), num_filter=256,
                         name="conv2")
    r2 = sym.Activation(c2, act_type="relu")
    l2 = sym.LRN(r2, alpha=1e-4, beta=0.75, knorm=2, nsize=5)
    p2 = sym.Pooling(l2, kernel=(3, 3), stride=(2, 2), pool_type="max")
    c3 = sym.Convolution(p2, kernel=(3, 3), pad=(1, 1), num_filter=384,
                         name="conv3")
    r3 = sym.Activation(c3, act_type="relu")
    c4 = sym.Convolution(r3, kernel=(3, 3), pad=(1, 1), num_filter=384,
                         name="conv4")
    r4 = sym.Activation(c4, act_type="relu")
    c5 = sym.Convolution(r4, kernel=(3, 3), pad=(1, 1), num_filter=256,
                         name="conv5")
    r5 = sym.Activation(c5, act_type="relu")
    p5 = sym.Pooling(r5, kernel=(3, 3), stride=(2, 2), pool_type="max")
    f = sym.Flatten(p5)
    fc6 = sym.FullyConnected(f, num_hidden=4096, name="fc6")
    r6 = sym.Activation(fc6, act_type="relu")
    d6 = sym.Dropout(r6, p=0.5)
    fc7 = sym.FullyConnected(d6, num_hidden=4096, name="fc7")
    r7 = sym.Activation(fc7, act_type="relu")
    d7 = sym.Dropout(r7, p=0.5)
    fc8 = sym.FullyConnected(d7, num_hidden=num_classes, name="fc8")
    return sym.SoftmaxOutput(fc8, name="softmax")


alexnet = get_symbol
