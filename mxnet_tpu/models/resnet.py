"""Symbolic ResNet builder (v1/v2), written TPU-first.

Role parity: the reference's example/image-classification/symbols/resnet.py
(training symbol used by train_imagenet.py and the perf tables in
docs/faq/perf.md). Fresh implementation: standard He/identity-mapping
residual topology expressed over our op registry; XLA fuses BN+ReLU into the
convs, so no manual fusion tricks are needed.
"""
from __future__ import annotations

from .. import symbol as sym

__all__ = ["get_symbol", "resnet"]


def _conv(data, num_filter, kernel, stride=(1, 1), pad=(0, 0), name=None):
    return sym.Convolution(data=data, num_filter=num_filter, kernel=kernel,
                           stride=stride, pad=pad, no_bias=True, name=name)


def _bn(data, name, fix_gamma=False):
    return sym.BatchNorm(data=data, fix_gamma=fix_gamma, eps=2e-5,
                         momentum=0.9, name=name)


def residual_unit_v1(data, num_filter, stride, dim_match, name, bottle_neck):
    if bottle_neck:
        conv1 = _conv(data, num_filter // 4, (1, 1), stride, (0, 0), name + "_conv1")
        bn1 = _bn(conv1, name + "_bn1")
        act1 = sym.Activation(bn1, act_type="relu")
        conv2 = _conv(act1, num_filter // 4, (3, 3), (1, 1), (1, 1), name + "_conv2")
        bn2 = _bn(conv2, name + "_bn2")
        act2 = sym.Activation(bn2, act_type="relu")
        conv3 = _conv(act2, num_filter, (1, 1), (1, 1), (0, 0), name + "_conv3")
        bn3 = _bn(conv3, name + "_bn3")
        body = bn3
    else:
        conv1 = _conv(data, num_filter, (3, 3), stride, (1, 1), name + "_conv1")
        bn1 = _bn(conv1, name + "_bn1")
        act1 = sym.Activation(bn1, act_type="relu")
        conv2 = _conv(act1, num_filter, (3, 3), (1, 1), (1, 1), name + "_conv2")
        bn2 = _bn(conv2, name + "_bn2")
        body = bn2
    if dim_match:
        shortcut = data
    else:
        sc = _conv(data, num_filter, (1, 1), stride, (0, 0), name + "_sc_conv")
        shortcut = _bn(sc, name + "_sc_bn")
    return sym.Activation(body + shortcut, act_type="relu")


def residual_unit_v2(data, num_filter, stride, dim_match, name, bottle_neck):
    bn1 = _bn(data, name + "_bn1")
    act1 = sym.Activation(bn1, act_type="relu")
    if bottle_neck:
        conv1 = _conv(act1, num_filter // 4, (1, 1), (1, 1), (0, 0), name + "_conv1")
        bn2 = _bn(conv1, name + "_bn2")
        act2 = sym.Activation(bn2, act_type="relu")
        conv2 = _conv(act2, num_filter // 4, (3, 3), stride, (1, 1), name + "_conv2")
        bn3 = _bn(conv2, name + "_bn3")
        act3 = sym.Activation(bn3, act_type="relu")
        body = _conv(act3, num_filter, (1, 1), (1, 1), (0, 0), name + "_conv3")
    else:
        conv1 = _conv(act1, num_filter, (3, 3), stride, (1, 1), name + "_conv1")
        bn2 = _bn(conv1, name + "_bn2")
        act2 = sym.Activation(bn2, act_type="relu")
        body = _conv(act2, num_filter, (3, 3), (1, 1), (1, 1), name + "_conv2")
    if dim_match:
        shortcut = data
    else:
        shortcut = _conv(act1, num_filter, (1, 1), stride, (0, 0), name + "_sc")
    return body + shortcut


_CONFIGS = {
    18: ([2, 2, 2, 2], False),
    34: ([3, 4, 6, 3], False),
    50: ([3, 4, 6, 3], True),
    101: ([3, 4, 23, 3], True),
    152: ([3, 8, 36, 3], True),
    20: ([3, 3, 3], False),       # CIFAR variants
    56: ([9, 9, 9], False),
    110: ([18, 18, 18], False),
}


def resnet(num_classes=1000, num_layers=50, version=1, image_shape=(3, 224, 224),
           dtype="float32"):
    units, bottle_neck = _CONFIGS[num_layers]
    cifar = len(units) == 3
    filter_list = ([16, 16, 32, 64] if cifar else
                   ([64, 256, 512, 1024, 2048] if bottle_neck
                    else [64, 64, 128, 256, 512]))
    unit = residual_unit_v2 if version == 2 else residual_unit_v1

    data = sym.Variable("data")
    if dtype != "float32":
        data = sym.Cast(data, dtype=dtype)
    if cifar:
        body = _conv(data, filter_list[0], (3, 3), (1, 1), (1, 1), "conv0")
        body = _bn(body, "bn0")
        body = sym.Activation(body, act_type="relu")
    else:
        body = _conv(data, filter_list[0], (7, 7), (2, 2), (3, 3), "conv0")
        body = _bn(body, "bn0")
        body = sym.Activation(body, act_type="relu")
        body = sym.Pooling(body, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                           pool_type="max")
    for i, n_units in enumerate(units):
        stride = (1, 1) if i == 0 else (2, 2)
        body = unit(body, filter_list[i + 1], stride, False,
                    "stage%d_unit1" % (i + 1), bottle_neck)
        for j in range(n_units - 1):
            body = unit(body, filter_list[i + 1], (1, 1), True,
                        "stage%d_unit%d" % (i + 1, j + 2), bottle_neck)
    if version == 2:
        body = _bn(body, "bn_final")
        body = sym.Activation(body, act_type="relu")
    pool = sym.Pooling(body, global_pool=True, kernel=(7, 7), pool_type="avg")
    flat = sym.Flatten(pool)
    fc = sym.FullyConnected(flat, num_hidden=num_classes, name="fc1")
    if dtype != "float32":
        fc = sym.Cast(fc, dtype="float32")
    return sym.SoftmaxOutput(fc, name="softmax")


def get_symbol(num_classes=1000, num_layers=50, image_shape="3,224,224",
               conv_workspace=256, dtype="float32", **kwargs):
    """reference-style entry (example/image-classification symbols API)."""
    if isinstance(image_shape, str):
        image_shape = tuple(int(x) for x in image_shape.split(","))
    version = kwargs.get("version", 1)
    return resnet(num_classes=num_classes, num_layers=num_layers,
                  version=version, image_shape=image_shape, dtype=dtype)
