"""Symbolic Inception-v3 builder, written TPU-first.

Role parity: the reference's example/image-classification/symbols/
inception-v3.py (the training symbol behind the Inception-v3 rows of
docs/faq/perf.md:228-237). Fresh implementation of the published
architecture (Szegedy et al., "Rethinking the Inception Architecture",
2015): factorized 7x7 stems and the A/B/C/D/E tower mix expressed over
this package's op registry — concat towers are single XLA fusions, so no
channel-split scheduling is needed.

Input is the canonical 3x299x299 (works down to 3x139x139).
"""
from __future__ import annotations

from .. import symbol as sym

__all__ = ["get_symbol", "inception_v3"]


def _cb(data, num_filter, kernel, stride=(1, 1), pad=(0, 0), name=""):
    """conv + BN + relu, the unit every tower is built from."""
    c = sym.Convolution(data=data, num_filter=num_filter, kernel=kernel,
                        stride=stride, pad=pad, no_bias=True,
                        name="%s_conv" % name)
    b = sym.BatchNorm(c, eps=0.001, fix_gamma=True, name="%s_bn" % name)
    return sym.Activation(b, act_type="relu")


def _pool(data, kernel, stride, pad=(0, 0), pool_type="max", name=""):
    return sym.Pooling(data, kernel=kernel, stride=stride, pad=pad,
                       pool_type=pool_type, name=name)


def _tower_a(data, pool_filters, name):
    """35x35 mix: 1x1 / 5x5 / double-3x3 / pool towers."""
    t1 = _cb(data, 64, (1, 1), name=name + "_t1_1x1")
    t2 = _cb(data, 48, (1, 1), name=name + "_t2_1x1")
    t2 = _cb(t2, 64, (5, 5), pad=(2, 2), name=name + "_t2_5x5")
    t3 = _cb(data, 64, (1, 1), name=name + "_t3_1x1")
    t3 = _cb(t3, 96, (3, 3), pad=(1, 1), name=name + "_t3_3x3a")
    t3 = _cb(t3, 96, (3, 3), pad=(1, 1), name=name + "_t3_3x3b")
    t4 = _pool(data, (3, 3), (1, 1), (1, 1), "avg", name + "_t4_pool")
    t4 = _cb(t4, pool_filters, (1, 1), name=name + "_t4_1x1")
    return sym.Concat(t1, t2, t3, t4, dim=1, name=name)


def _tower_b(data, name):
    """35x35 -> 17x17 grid reduction."""
    t1 = _cb(data, 384, (3, 3), stride=(2, 2), name=name + "_t1_3x3")
    t2 = _cb(data, 64, (1, 1), name=name + "_t2_1x1")
    t2 = _cb(t2, 96, (3, 3), pad=(1, 1), name=name + "_t2_3x3a")
    t2 = _cb(t2, 96, (3, 3), stride=(2, 2), name=name + "_t2_3x3b")
    t3 = _pool(data, (3, 3), (2, 2), name=name + "_t3_pool")
    return sym.Concat(t1, t2, t3, dim=1, name=name)


def _tower_c(data, c7, name):
    """17x17 mix with factorized 7x7 (1x7 then 7x1)."""
    t1 = _cb(data, 192, (1, 1), name=name + "_t1_1x1")
    t2 = _cb(data, c7, (1, 1), name=name + "_t2_1x1")
    t2 = _cb(t2, c7, (1, 7), pad=(0, 3), name=name + "_t2_1x7")
    t2 = _cb(t2, 192, (7, 1), pad=(3, 0), name=name + "_t2_7x1")
    t3 = _cb(data, c7, (1, 1), name=name + "_t3_1x1")
    t3 = _cb(t3, c7, (7, 1), pad=(3, 0), name=name + "_t3_7x1a")
    t3 = _cb(t3, c7, (1, 7), pad=(0, 3), name=name + "_t3_1x7a")
    t3 = _cb(t3, c7, (7, 1), pad=(3, 0), name=name + "_t3_7x1b")
    t3 = _cb(t3, 192, (1, 7), pad=(0, 3), name=name + "_t3_1x7b")
    t4 = _pool(data, (3, 3), (1, 1), (1, 1), "avg", name + "_t4_pool")
    t4 = _cb(t4, 192, (1, 1), name=name + "_t4_1x1")
    return sym.Concat(t1, t2, t3, t4, dim=1, name=name)


def _tower_d(data, name):
    """17x17 -> 8x8 grid reduction."""
    t1 = _cb(data, 192, (1, 1), name=name + "_t1_1x1")
    t1 = _cb(t1, 320, (3, 3), stride=(2, 2), name=name + "_t1_3x3")
    t2 = _cb(data, 192, (1, 1), name=name + "_t2_1x1")
    t2 = _cb(t2, 192, (1, 7), pad=(0, 3), name=name + "_t2_1x7")
    t2 = _cb(t2, 192, (7, 1), pad=(3, 0), name=name + "_t2_7x1")
    t2 = _cb(t2, 192, (3, 3), stride=(2, 2), name=name + "_t2_3x3")
    t3 = _pool(data, (3, 3), (2, 2), name=name + "_t3_pool")
    return sym.Concat(t1, t2, t3, dim=1, name=name)


def _tower_e(data, name):
    """8x8 mix with expanded 3x3 (1x3 + 3x1 branches concatenated)."""
    t1 = _cb(data, 320, (1, 1), name=name + "_t1_1x1")
    t2 = _cb(data, 384, (1, 1), name=name + "_t2_1x1")
    t2a = _cb(t2, 384, (1, 3), pad=(0, 1), name=name + "_t2_1x3")
    t2b = _cb(t2, 384, (3, 1), pad=(1, 0), name=name + "_t2_3x1")
    t3 = _cb(data, 448, (1, 1), name=name + "_t3_1x1")
    t3 = _cb(t3, 384, (3, 3), pad=(1, 1), name=name + "_t3_3x3")
    t3a = _cb(t3, 384, (1, 3), pad=(0, 1), name=name + "_t3_1x3")
    t3b = _cb(t3, 384, (3, 1), pad=(1, 0), name=name + "_t3_3x1")
    t4 = _pool(data, (3, 3), (1, 1), (1, 1), "avg", name + "_t4_pool")
    t4 = _cb(t4, 192, (1, 1), name=name + "_t4_1x1")
    return sym.Concat(t1, t2a, t2b, t3a, t3b, t4, dim=1, name=name)


def get_symbol(num_classes=1000, dropout=0.5, **kwargs):
    data = sym.Variable("data")
    # factorized stem: 299x299x3 -> 35x35x192
    net = _cb(data, 32, (3, 3), stride=(2, 2), name="stem1")
    net = _cb(net, 32, (3, 3), name="stem2")
    net = _cb(net, 64, (3, 3), pad=(1, 1), name="stem3")
    net = _pool(net, (3, 3), (2, 2), name="stem_pool1")
    net = _cb(net, 80, (1, 1), name="stem4")
    net = _cb(net, 192, (3, 3), name="stem5")
    net = _pool(net, (3, 3), (2, 2), name="stem_pool2")
    # 3x A (35x35), reduce, 4x C (17x17), reduce, 2x E (8x8)
    net = _tower_a(net, 32, "mixed0")
    net = _tower_a(net, 64, "mixed1")
    net = _tower_a(net, 64, "mixed2")
    net = _tower_b(net, "mixed3")
    net = _tower_c(net, 128, "mixed4")
    net = _tower_c(net, 160, "mixed5")
    net = _tower_c(net, 160, "mixed6")
    net = _tower_c(net, 192, "mixed7")
    net = _tower_d(net, "mixed8")
    net = _tower_e(net, "mixed9")
    net = _tower_e(net, "mixed10")
    net = sym.Pooling(net, kernel=(8, 8), global_pool=True,
                      pool_type="avg", name="global_pool")
    if dropout:
        net = sym.Dropout(net, p=dropout)
    net = sym.Flatten(net)
    net = sym.FullyConnected(net, num_hidden=num_classes, name="fc1")
    return sym.SoftmaxOutput(net, name="softmax")


inception_v3 = get_symbol
