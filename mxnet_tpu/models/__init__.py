"""Symbolic model builders (role parity:
example/image-classification/symbols/ in the reference)."""
from . import resnet
from .resnet import get_symbol as resnet_symbol
from .inception_v3 import get_symbol as inception_v3_symbol
from .alexnet import get_symbol as alexnet_symbol


def lenet(num_classes=10):
    """LeNet (reference example/image-classification/train_mnist.py model)."""
    from .. import symbol as sym
    data = sym.Variable("data")
    c1 = sym.Convolution(data=data, kernel=(5, 5), num_filter=20, name="conv1")
    t1 = sym.Activation(c1, act_type="tanh")
    p1 = sym.Pooling(t1, pool_type="max", kernel=(2, 2), stride=(2, 2))
    c2 = sym.Convolution(p1, kernel=(5, 5), num_filter=50, name="conv2")
    t2 = sym.Activation(c2, act_type="tanh")
    p2 = sym.Pooling(t2, pool_type="max", kernel=(2, 2), stride=(2, 2))
    f = sym.Flatten(p2)
    fc1 = sym.FullyConnected(f, num_hidden=500, name="fc1")
    t3 = sym.Activation(fc1, act_type="tanh")
    fc2 = sym.FullyConnected(t3, num_hidden=num_classes, name="fc2")
    return sym.SoftmaxOutput(fc2, name="softmax")


def mlp(num_classes=10, hidden=(128, 64)):
    """reference example/image-classification/train_mnist.py mlp."""
    from .. import symbol as sym
    net = sym.Variable("data")
    net = sym.Flatten(net)
    for i, h in enumerate(hidden):
        net = sym.FullyConnected(net, num_hidden=h, name="fc%d" % (i + 1))
        net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=num_classes,
                             name="fc%d" % (len(hidden) + 1))
    return sym.SoftmaxOutput(net, name="softmax")
