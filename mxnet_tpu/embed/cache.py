"""Device-resident hot-row embedding cache with host-side spill.

The Zipf reality of recommendation traffic: a few percent of rows take
almost all lookups. This module keeps those hot rows in a fixed
``(capacity, dim)`` device buffer updated IN PLACE (donated scatter,
the PR-9 paged-KV-cache discipline) and spills the cold tail to a host
:class:`SpillStore`, so the *logical* table is bounded by host+device
memory together — and, with a lazy row initializer, only by the rows
actually touched.

Budget discipline (PR 3): all placement decisions — hit/miss tests, LRU
eviction, slot assignment — happen on HOST metadata (a dict and an
order list), never by reading the device buffer. The per-step device
traffic is: one donated h2d scatter uploading missed rows, and (only
in training, only on eviction of a DIRTY row) a d2h pull of the evicted
rows for write-back. Serving is read-only — rows are never dirty, so
the served lookup performs ZERO d2h, which mxlint MXL511 pins on the
lowered program. Hit/miss/spill counters are plain ints published per
K-step window through ``telemetry.publish_window(embed=...)``.

Bitwise across capacities: a row's update arithmetic depends only on
its value and its gradient, never on which slot it sits in or when it
was evicted (the d2h/h2d spill round-trip preserves bits), so training
the same stream with capacity 8 or 64 lands identical final tables —
the chip-free gate in tests/test_embed.py.
"""
from __future__ import annotations

from collections import OrderedDict

import numpy as _np

from ..base import MXNetError
from .table import row_init

__all__ = ["HotRowCache", "SpillStore"]


class SpillStore:
    """Host-side cold-row store, lazily materialized.

    Rows live in a dict only once touched; an untouched row costs
    nothing and is (re)created deterministically by ``init_fn(ids)`` —
    by default :func:`row_init`, the same bits every mesh shard or
    reference run would produce. ``budget_bytes`` (optional,
    ``MXNET_EMBED_HOST_BUDGET_MB`` via the caller) bounds RESIDENT host
    bytes: the store raises rather than silently blowing past it, which
    is how the fleet test proves the logical table exceeds the
    configured host budget while training stays inside it."""

    def __init__(self, rows, dim, dtype="float32", init_fn=None, seed=0,
                 budget_bytes=None):
        self.rows = int(rows)
        self.dim = int(dim)
        self.dtype = _np.dtype(dtype)
        self.seed = int(seed)
        self._init_fn = init_fn
        self.budget_bytes = (None if budget_bytes is None
                             else int(budget_bytes))
        self._rows = {}
        self.row_bytes = self.dim * self.dtype.itemsize

    @property
    def logical_bytes(self):
        """Bytes a dense materialization of the table would take."""
        return self.rows * self.row_bytes

    @property
    def resident_bytes(self):
        """Bytes actually held on host right now."""
        return len(self._rows) * self.row_bytes

    def _materialize(self, ids):
        if self._init_fn is not None:
            return _np.asarray(self._init_fn(ids),
                               dtype=self.dtype).reshape(len(ids),
                                                         self.dim)
        return row_init(self.seed, ids, self.dim, self.dtype)

    def take(self, ids):
        """Pop rows (id array -> (n, dim)); cold ids are materialized.
        Rows move to the device cache EXCLUSIVELY — host memory shrinks
        by what the device now holds."""
        out = _np.empty((len(ids), self.dim), dtype=self.dtype)
        fresh = [i for i in ids if int(i) not in self._rows]
        if fresh:
            made = self._materialize(_np.asarray(fresh, _np.int64))
            for j, i in enumerate(fresh):
                self._rows[int(i)] = made[j]
        for j, i in enumerate(ids):
            out[j] = self._rows.pop(int(i))
        return out

    def put(self, ids, values):
        """Write evicted rows back (the training spill path)."""
        values = _np.asarray(values, dtype=self.dtype)
        for j, i in enumerate(ids):
            self._rows[int(i)] = _np.array(values[j], copy=True)
        if (self.budget_bytes is not None
                and self.resident_bytes > self.budget_bytes):
            raise MXNetError(
                "embed: host spill store exceeded its configured budget "
                "(%d resident > %d budget bytes; logical table is %d) — "
                "raise MXNET_EMBED_HOST_BUDGET_MB or the cache capacity"
                % (self.resident_bytes, self.budget_bytes,
                   self.logical_bytes))

    def peek(self, ids):
        """Read rows without removing them (debug/final-state export)."""
        out = _np.empty((len(ids), self.dim), dtype=self.dtype)
        fresh = [i for i in ids if int(i) not in self._rows]
        if fresh:
            made = self._materialize(_np.asarray(fresh, _np.int64))
            for j, i in enumerate(fresh):
                self._rows[int(i)] = made[j]
        for j, i in enumerate(ids):
            out[j] = self._rows[int(i)]
        return out


class HotRowCache:
    """Fixed-capacity device cache over a :class:`SpillStore`.

    Protocol per step (the two-tower trainer and the recommend engine
    both follow it)::

        slots = cache.ensure(ids)      # host plan + spill I/O
        out, cache.buf = step(cache.buf, slots, ...)   # donated jit
        cache.note_updated(ids)        # training only: mark dirty

    ``ensure`` is the only method that moves data: it evicts LRU rows
    (pulling DIRTY ones device->host first — the accounted d2h), uploads
    missed rows with ONE donated scatter, and returns the device slot of
    every requested id. The jitted step receives SLOT ids, so its
    lowering is capacity-shaped, never rows-shaped — that is what lets
    the logical table outgrow the device."""

    def __init__(self, store, capacity, pad_to=8):
        if capacity <= 0:
            raise MXNetError("HotRowCache: capacity must be positive")
        if capacity > store.rows:
            capacity = store.rows
        self.store = store
        self.capacity = int(capacity)
        self.dim = store.dim
        self.dtype = store.dtype
        # upload batches are padded to multiples of pad_to so the
        # donated scatter compiles O(log capacity) variants, not one
        # per distinct miss count
        self.pad_to = max(1, int(pad_to))
        self._slot_of = {}            # id -> slot
        self._id_of = [-1] * self.capacity
        self._lru = OrderedDict()     # id -> None, oldest first
        self._free = list(range(self.capacity - 1, -1, -1))
        self._dirty = set()
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.spill_bytes = 0          # d2h write-back volume
        self.upload_bytes = 0         # h2d fill volume
        self.lookups = 0
        import jax
        self.buf = jax.device_put(
            _np.zeros((self.capacity, self.dim), dtype=self.dtype))
        self._scatter = jax.jit(
            lambda buf, slots, rows: buf.at[slots].set(rows),
            donate_argnums=(0,))

    # -- the per-step plan ---------------------------------------------------
    def ensure(self, ids):
        """Make every id device-resident; returns np.int32 slots aligned
        with ``ids`` (duplicates map to the same slot)."""
        ids = _np.clip(_np.asarray(ids, _np.int64).reshape(-1),
                       0, self.store.rows - 1)
        uniq = list(dict.fromkeys(int(i) for i in ids))  # order-stable
        if len(uniq) > self.capacity:
            raise MXNetError(
                "embed: one step touches %d distinct rows but the cache "
                "holds %d — raise capacity above the per-step working "
                "set (docs/embeddings.md cache sizing)" % (len(uniq),
                                                           self.capacity))
        self.lookups += len(ids)
        missing = []
        for i in uniq:
            if i in self._slot_of:
                self.hits += 1
                self._lru.move_to_end(i)
            else:
                self.misses += 1
                missing.append(i)
        if missing:
            self._fill(missing, protect=set(uniq))
        slots = _np.fromiter((self._slot_of[int(i)] for i in ids),
                             dtype=_np.int32, count=len(ids))
        return slots

    def _fill(self, missing, protect):
        import jax
        from .. import profiler
        need = len(missing) - len(self._free)
        if need > 0:
            evict = []
            for i in list(self._lru):
                if len(evict) == need:
                    break
                if i in protect:
                    continue
                evict.append(i)
            dirty = [i for i in evict if i in self._dirty]
            if dirty:
                d_slots = _np.asarray(
                    [self._slot_of[i] for i in dirty], _np.int32)
                # the ONLY d2h on this path, and only in training:
                # evicted dirty rows spill back to the host store
                vals = _np.asarray(jax.device_get(self.buf[d_slots]))
                nbytes = vals.nbytes
                profiler.record_host_sync("d2h", nbytes)
                self.spill_bytes += nbytes
                self.store.put(dirty, vals)
            for i in evict:
                self.evictions += 1
                slot = self._slot_of.pop(i)
                self._id_of[slot] = -1
                self._lru.pop(i, None)
                self._dirty.discard(i)
                self._free.append(slot)
        rows = self.store.take(missing)
        slots = []
        for i in missing:
            slot = self._free.pop()
            self._slot_of[i] = slot
            self._id_of[slot] = i
            self._lru[i] = None
            slots.append(slot)
        # pad to the bucket so the donated scatter's jit cache stays
        # small; padding re-writes the first row with its own value
        m = len(missing)
        pad = -(-m // self.pad_to) * self.pad_to - m
        if pad:
            slots = slots + [slots[0]] * pad
            rows = _np.concatenate([rows, _np.repeat(rows[:1], pad, 0)])
        self.upload_bytes += rows.nbytes
        self.buf = self._scatter(self.buf,
                                 _np.asarray(slots, _np.int32), rows)

    def note_updated(self, ids):
        """Training: the step's donated scatter rewrote these rows on
        device; they must spill before their slot is reused."""
        for i in _np.asarray(ids, _np.int64).reshape(-1):
            i = int(min(max(i, 0), self.store.rows - 1))
            if i in self._slot_of:
                self._dirty.add(i)

    def flush(self):
        """Spill every dirty row to the host store (end of training /
        checkpoint). One d2h for the whole dirty set."""
        import jax
        from .. import profiler
        dirty = sorted(self._dirty)
        if not dirty:
            return 0
        slots = _np.asarray([self._slot_of[i] for i in dirty], _np.int32)
        vals = _np.asarray(jax.device_get(self.buf[slots]))
        profiler.record_host_sync("d2h", vals.nbytes)
        self.spill_bytes += vals.nbytes
        self.store.put(dirty, vals)
        self._dirty.clear()
        return len(dirty)

    def hit_rate(self):
        n = self.hits + self.misses
        return (self.hits / n) if n else 0.0

    def stats(self):
        """Host-held counters — the ``embed/*`` telemetry source; never
        reads the device."""
        return {
            "capacity": self.capacity,
            "resident": len(self._slot_of),
            "hits": self.hits,
            "misses": self.misses,
            "evictions": self.evictions,
            "hit_rate": round(self.hit_rate(), 6),
            "spill_bytes": self.spill_bytes,
            "upload_bytes": self.upload_bytes,
            "lookups": self.lookups,
            "host_resident_bytes": self.store.resident_bytes,
            "logical_bytes": self.store.logical_bytes,
        }
