"""Mesh-partitioned embedding tables with an all-to-all lookup core.

A (rows x dim) table is ROW-sharded across the flattened ``dp``/``tp``
mesh (every device owns a contiguous ``rows_per_shard`` stripe), so the
aggregate table is bounded by fleet HBM, not one chip's. The lookup is
a pure function designed to run INSIDE ``shard_map`` — the same manual
collectives discipline as the PR-8 ``SPMDTrainStep`` ``ddp_bucketed``
step, so the two compose under one mesh:

1. clip ids to the logical row range (the take/Embedding contract —
   dispatch must never change numerics, docs/embeddings.md);
2. bucket ids by OWNER shard (``id // rows_per_shard``) with a stable
   sort, scatter them into a fixed ``(shards, capacity)`` send buffer
   (all-to-all needs equal splits; capacity = the local id count, the
   worst case of every id hashing to one owner);
3. ``jax.lax.all_to_all`` the id buffer, gather the owned rows locally
   through the PR-6 scalar-prefetch kernel tier (D%128 guard and clip
   semantics preserved — :func:`local_gather`), all-to-all the rows
   back, and unpermute.

Determinism is load-bearing, not incidental: the transpose of this
program scatter-adds gradient contributions into each owner stripe in
(source-rank, batch-position) order — exactly the left-fold a 1-rank
``jnp.take`` VJP performs over the same global batch — so training is
**bitwise-equal across shardings** (the chip-free fleet gate in
tests/test_embed.py). That only holds because the sort is stable and
the send-buffer layout is position-ordered; keep it that way.
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError

__all__ = ["ShardedEmbedding", "sharded_lookup", "local_gather",
           "row_init"]


def row_init(seed, row_ids, dim, dtype="float32", scale=0.01):
    """Deterministic PER-ROW initializer: row ``r`` has the same bits
    whether it is materialized by a mesh shard, the host spill store's
    first touch, or a 1-rank reference run — the property every
    bitwise-across-shardings/capacities test leans on. Counter-based
    (Philox keyed by (seed, row)), so cost is per *touched* row and
    order-independent."""
    rows = _np.atleast_1d(_np.asarray(row_ids, dtype=_np.int64))
    out = _np.empty((rows.size, dim), dtype=_np.dtype(dtype))
    for i, r in enumerate(rows):
        g = _np.random.Generator(_np.random.Philox(key=[seed, int(r)]))
        out[i] = (g.standard_normal(dim) * scale).astype(out.dtype)
    return out


def local_gather(shard, idx):
    """Row gather on one shard through the kernel tier.

    ``idx`` must already be clipped to the shard's local range — both
    the Pallas scalar-prefetch kernel and the ``jnp.take(mode="clip")``
    fallback clamp, so dispatch never changes out-of-range numerics
    (the ops/nn.py Embedding contract; tests/test_embed.py pins the
    kernel/fallback parity on OOB ids, fwd AND grad)."""
    import jax.numpy as jnp
    from ..kernels import tier as _ktier
    if _ktier.enabled():
        from ..kernels import take as _ktake
        reason = _ktake.eligible(shard.shape, shard.dtype, idx.shape,
                                 idx.dtype)
        go, cfg = _ktier.should_dispatch(
            _ktake.OP_NAME,
            _ktake.shape_key_shapes(shard.shape, idx.shape),
            shard.dtype, guard_reason=reason)
        if go:
            return _ktake.take_rows(shard, idx, config=cfg)
    return jnp.take(shard, idx.astype(jnp.int32), axis=0, mode="clip")


def sharded_lookup(shard, ids, *, rows, rows_per_shard, num_shards,
                   axis_name):
    """Pure lookup core for use inside ``shard_map``.

    ``shard`` is this device's ``(rows_per_shard, dim)`` stripe; ``ids``
    is its local slice of the batch (any int shape), holding GLOBAL row
    ids. Returns ``ids.shape + (dim,)`` embeddings. ``axis_name`` may be
    one mesh axis or a tuple (the flattened ``("dp", "tp")`` mesh);
    ``num_shards`` is the product of those axis sizes. Single-shard
    meshes short-circuit to a local gather — no collectives, so the
    1-rank path is exactly the dense ``take``."""
    import jax
    import jax.numpy as jnp

    id_shape = ids.shape
    flat = jnp.clip(ids.astype(jnp.int32).reshape(-1), 0, rows - 1)
    if num_shards == 1:
        out = local_gather(shard, flat)
        return out.reshape(id_shape + (shard.shape[-1],))
    cap = flat.shape[0]              # per-peer capacity (worst case)
    me = jax.lax.axis_index(axis_name)
    owner = flat // rows_per_shard   # already < num_shards (ids clipped)
    # stable sort by owner: within one owner bucket the batch-position
    # order survives, which is what makes the transpose's scatter-add a
    # position-ordered left fold (see module docstring)
    order = jnp.argsort(owner, stable=True)
    s_owner = owner[order]
    s_ids = flat[order]
    counts = jnp.bincount(owner, length=num_shards).astype(jnp.int32)
    starts = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32),
         jnp.cumsum(counts)[:-1].astype(jnp.int32)])
    slot = jnp.arange(cap, dtype=jnp.int32) - starts[s_owner]
    dest = s_owner * cap + slot
    send = jnp.zeros((num_shards * cap,), jnp.int32).at[dest].set(s_ids)
    # row p of the received buffer = the ids peer p wants from my stripe
    want = jax.lax.all_to_all(send.reshape(num_shards, cap),
                              axis_name, 0, 0)
    loc = jnp.clip(want.reshape(-1) - me * rows_per_shard,
                   0, rows_per_shard - 1)
    rows_out = local_gather(shard, loc)
    rows_out = rows_out.reshape(num_shards, cap, shard.shape[-1])
    # row j of the return = my requested rows, in the order I sent them
    back = jax.lax.all_to_all(rows_out, axis_name, 0, 0)
    back = back.reshape(num_shards * cap, shard.shape[-1])
    gather_at = jnp.zeros((cap,), jnp.int32).at[order].set(dest)
    return back[gather_at].reshape(id_shape + (shard.shape[-1],))


class ShardedEmbedding:
    """A (rows x dim) table row-sharded over a mesh.

    Holds the STATIC plan only (padded rows, stripe size, axis names,
    partition specs) — parameters stay in the caller's pytree like every
    other mxnet_tpu layer, so checkpointing/donation/DDP treat the table
    like any param. ``mesh=None`` is the 1-rank layout (no collectives).

    Typical shard_map composition (the two-tower trainer)::

        emb = ShardedEmbedding(rows, dim, mesh=mesh,
                               axis_names=("dp", "tp"))
        table = emb.init(seed)                    # np (padded_rows, dim)
        def step(table_shard, ids_local, ...):    # inside shard_map
            vecs = emb.lookup(table_shard, ids_local)
            ...
        shard_map(step, mesh=mesh,
                  in_specs=(emb.table_spec, P(emb.axis_names), ...), ...)
    """

    def __init__(self, rows, dim, mesh=None, axis_names=None,
                 dtype="float32", seed=0, name="embed"):
        if rows <= 0 or dim <= 0:
            raise MXNetError("ShardedEmbedding: rows and dim must be "
                             "positive (got %d x %d)" % (rows, dim))
        self.rows = int(rows)
        self.dim = int(dim)
        self.dtype = _np.dtype(dtype)
        self.mesh = mesh
        self.seed = int(seed)
        self.name = name
        if mesh is None:
            self.axis_names = ()
            self.num_shards = 1
        else:
            names = tuple(axis_names) if axis_names else tuple(
                mesh.axis_names)
            for ax in names:
                if ax not in mesh.axis_names:
                    raise MXNetError(
                        "ShardedEmbedding: axis %r not in mesh axes %s"
                        % (ax, tuple(mesh.axis_names)))
            self.axis_names = names
            self.num_shards = int(_np.prod(
                [mesh.shape[ax] for ax in names], dtype=_np.int64))
        # pad the stripe so every shard is equal-sized; padded rows are
        # unreachable (ids clip to rows-1) and their grads are zero
        self.rows_per_shard = -(-self.rows // self.num_shards)
        self.padded_rows = self.rows_per_shard * self.num_shards

    @property
    def axis_name(self):
        """The all-to-all axis argument: one name or the tuple."""
        if self.num_shards == 1:
            return None
        return (self.axis_names[0] if len(self.axis_names) == 1
                else self.axis_names)

    @property
    def table_spec(self):
        """PartitionSpec for the (padded_rows, dim) table."""
        from jax.sharding import PartitionSpec as P
        if self.num_shards == 1:
            return P(None, None)
        return P(self.axis_name, None)

    def init(self, seed=None):
        """Full (padded_rows, dim) host table from :func:`row_init` —
        bitwise-identical rows to what a spill store or another mesh
        shape would materialize for the same seed."""
        seed = self.seed if seed is None else int(seed)
        tab = _np.zeros((self.padded_rows, self.dim), dtype=self.dtype)
        tab[:self.rows] = row_init(seed, _np.arange(self.rows),
                                   self.dim, self.dtype)
        return tab

    def device_put(self, table):
        """Place a host table onto the mesh with the row sharding."""
        import jax
        if self.mesh is None:
            return jax.device_put(table)
        from jax.sharding import NamedSharding
        return jax.device_put(
            table, NamedSharding(self.mesh, self.table_spec))

    def lookup(self, shard, ids):
        """The pure core, pre-bound to this table's plan. Call inside
        ``shard_map`` (or anywhere when ``mesh=None``)."""
        return sharded_lookup(
            shard, ids, rows=self.rows,
            rows_per_shard=self.rows_per_shard,
            num_shards=self.num_shards,
            axis_name=self.axis_name if self.num_shards > 1 else "_")

    def make_lookup(self):
        """A jitted standalone ``(table, ids) -> vecs`` over the mesh
        (shard_map-wrapped when sharded) — the serving-side and test
        entry point; training steps inline :meth:`lookup` instead."""
        import jax
        if self.num_shards == 1:
            return jax.jit(self.lookup)
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P
        fn = shard_map(
            self.lookup, mesh=self.mesh,
            in_specs=(self.table_spec, P(self.axis_name)),
            out_specs=P(self.axis_name), check_rep=False)
        return jax.jit(fn)

    def comm_bytes_per_lookup(self, batch_ids):
        """Host-held all-to-all volume estimate for one lookup of
        ``batch_ids`` ids: the id exchange plus the row return (each
        crosses the mesh once). Telemetry/bench material — never a
        device read."""
        if self.num_shards == 1:
            return 0
        cap = -(-int(batch_ids) // self.num_shards) * self.num_shards
        ids_b = cap * self.num_shards * 4
        rows_b = cap * self.num_shards * self.dim * self.dtype.itemsize
        return ids_b + rows_b

    def __repr__(self):
        return ("ShardedEmbedding(%dx%d, shards=%d, stripe=%d, axes=%s)"
                % (self.rows, self.dim, self.num_shards,
                   self.rows_per_shard, list(self.axis_names)))
