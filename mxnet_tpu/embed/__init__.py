"""Sharded embedding subsystem: mesh-partitioned tables, hot-row
device cache with host spill, and the recommend serving leg.

The recommender workload (ROADMAP item 4) is defined by three
asymmetries the dense training stack has no answer for: tables bigger
than one chip's HBM (row-shard them across the ``dp``/``tp`` mesh —
:mod:`.table`), hot-key skew (keep the hot rows device-resident and
spill the cold tail to host — :mod:`.cache`), and gradients touching a
few thousand of millions of rows (exchange contributions, not tables —
the sparse bucket kind in :mod:`mxnet_tpu.parallel.ddp`). The serving
half (:mod:`.serve`) packages a trained two-tower retrieval head as a
format_version-6 ``.mxtpu`` artifact whose user table is *not* baked
into the program: it streams through the hot-row cache, which is what
``/v1/recommend`` (serve/http.py) runs and mxlint MXL511 disciplines.

docs/embeddings.md is the user guide.
"""
from __future__ import annotations

from .table import (ShardedEmbedding, sharded_lookup, local_gather,
                    row_init)
from .cache import HotRowCache, SpillStore

__all__ = ["ShardedEmbedding", "sharded_lookup", "local_gather",
           "row_init", "HotRowCache", "SpillStore"]
