"""The recommend serving leg: retrieval-tower artifacts + cached engine.

A trained two-tower retriever is two embedding tables: the USER tower
(averaged history embeddings) and the ITEM corpus it scores against.
``export_recommend`` packages both as a format_version-6 ``.mxtpu``
artifact — but unlike predict artifacts the user table is **not baked
into a compiled program**: production user tables outgrow any
bake-time constant, so the artifact carries the table as data and the
serving engine streams it through the PR-15 hot-row cache
(:class:`mxnet_tpu.embed.cache.HotRowCache`).

:class:`RecommendEngine` is what ``Server`` (mode="recommend") and
``POST /v1/recommend`` drive: per batch it plans slots on host
(hit/miss/spill accounting — zero device reads), uploads misses with
one donated scatter, then runs ONE jitted capacity-shaped program —
gather user rows from the cache, masked-mean, score the corpus matmul,
``top_k`` — and performs ONE d2h for the whole response batch. mxlint
MXL511 (``embedding_lookup_discipline_pass``) pins the lowering: the
cache buffer must be donated and the program must contain zero
device->host ops.

Cost model: a recommend request is charged by its GATHER count through
``perfmodel.recommend_request_seconds`` — the admission queue bills in
gather units and the fleet heartbeat's ``load_s`` is pending gathers
times the per-gather roofline, so the router's least-loaded policy
sees ragged requests honestly (docs/embeddings.md, docs/serving.md).
"""
from __future__ import annotations

import io
import json
import struct

import numpy as _np

from ..base import MXNetError
from ..config import flags
from .cache import HotRowCache, SpillStore

__all__ = ["export_recommend", "RecommendModel", "RecommendEngine"]


def export_recommend(user_table, item_table, path, *, max_ids=64, k=10,
                     model_name="twotower", extra_meta=None):
    """Write a format_version-6 recommend artifact.

    ``user_table`` (rows x dim) and ``item_table`` (items x dim) are
    host arrays (the trained parameters — flush the training cache
    first). ``max_ids`` bounds one request's history length; ``k`` is
    the default result count. The payload is a raw ``.npz`` (tables as
    DATA, not program constants); meta carries the geometry the serving
    engine and ``/info`` need."""
    from ..serving import _MAGIC
    user_table = _np.ascontiguousarray(user_table)
    item_table = _np.ascontiguousarray(item_table)
    if user_table.ndim != 2 or item_table.ndim != 2:
        raise MXNetError("export_recommend: tables must be 2-D "
                         "(rows x dim)")
    if user_table.shape[1] != item_table.shape[1]:
        raise MXNetError(
            "export_recommend: tower dims disagree (%d vs %d)"
            % (user_table.shape[1], item_table.shape[1]))
    meta = {
        "format_version": 6,
        "model_name": model_name,
        "recommend": {
            "rows": int(user_table.shape[0]),
            "items": int(item_table.shape[0]),
            "dim": int(user_table.shape[1]),
            "dtype": str(user_table.dtype),
            "max_ids": int(max_ids),
            "k": int(min(k, item_table.shape[0])),
        },
    }
    if extra_meta:
        meta.update(extra_meta)
    buf = io.BytesIO()
    _np.savez(buf, user_table=user_table, item_table=item_table)
    blob = buf.getvalue()
    meta_b = json.dumps(meta, sort_keys=True).encode()
    with open(path, "wb") as f:
        f.write(_MAGIC)
        f.write(struct.pack("<I", len(meta_b)))
        f.write(meta_b)
        f.write(blob)
    return meta


class RecommendModel:
    """A loaded format_version-6 artifact: geometry + host tables."""

    def __init__(self, meta, user_table, item_table):
        self.meta = meta
        self.spec = dict(meta["recommend"])
        self.user_table = user_table
        self.item_table = item_table

    @classmethod
    def load(cls, path, **_kw):
        from ..serving import _read_artifact, _require_kind
        meta, payload = _read_artifact(path)
        _require_kind(path, meta, "recommend")
        with _np.load(io.BytesIO(payload)) as z:
            user = z["user_table"]
            item = z["item_table"]
        return cls(meta, user, item)

    def engine(self, capacity=None, buckets=None, max_ids=None, k=None):
        return RecommendEngine(self, capacity=capacity, buckets=buckets,
                               max_ids=max_ids, k=k)


class RecommendEngine:
    """Cache-backed scorer over one :class:`RecommendModel`.

    ``buckets`` are request-batch buckets (like the predict micro-
    batcher's); each compiles one capacity-shaped executable. The user
    table lives in a :class:`HotRowCache` sized ``capacity``
    (``MXNET_EMBED_CACHE_ROWS`` default); the item corpus is small by
    construction (it is the output vocabulary) and sits dense on
    device."""

    def __init__(self, model, capacity=None, buckets=None, max_ids=None,
                 k=None):
        import jax
        self.model = model
        spec = model.spec
        self.rows = spec["rows"]
        self.dim = spec["dim"]
        self.items = spec["items"]
        self.max_ids = int(max_ids or spec["max_ids"])
        self.k = int(min(k or spec["k"], self.items))
        self.buckets = tuple(sorted(set(int(b) for b in
                                        (buckets or (1, 4, 16)))))
        capacity = int(capacity or flags.embed_cache_rows)
        budget = float(flags.embed_host_budget_mb or 0.0)
        user = model.user_table
        store = SpillStore(
            self.rows, self.dim, dtype=user.dtype,
            init_fn=lambda ids: user[_np.asarray(ids, _np.int64)],
            budget_bytes=int(budget * (1 << 20)) if budget > 0 else None)
        self.cache = HotRowCache(store, capacity)
        self.corpus = jax.device_put(_np.ascontiguousarray(
            model.item_table))
        self._jits = {}
        self.requests = 0
        self.gathers = 0

    # -- the served lookup program ------------------------------------------
    def _score_fn(self):
        """(cache_buf, corpus, slots, lengths) -> (cache_buf, scores,
        ids). The cache buffer is DONATED and threaded through — the
        resident buffer is never copied (MXL511's first check); slot
        ids keep the program capacity-shaped."""
        import jax
        import jax.numpy as jnp
        from .table import local_gather
        k = self.k
        max_ids = self.max_ids

        def run(cache_buf, corpus, slots, lengths):
            b = slots.shape[0]
            emb = local_gather(cache_buf, slots.reshape(-1))
            emb = emb.reshape(b, max_ids, cache_buf.shape[-1])
            mask = (jnp.arange(max_ids)[None, :]
                    < lengths[:, None]).astype(emb.dtype)
            denom = jnp.maximum(lengths.astype(emb.dtype), 1.0)
            user = (emb * mask[..., None]).sum(axis=1) / denom[:, None]
            scores = user @ corpus.T
            top_s, top_i = jax.lax.top_k(scores, k)
            return cache_buf, top_s, top_i

        return jax.jit(run, donate_argnums=(0,))

    def _jit(self, bucket):
        fn = self._jits.get(bucket)
        if fn is None:
            fn = self._jits[bucket] = self._score_fn()
        return fn

    def warm(self, bucket=None):
        """Compile (and run once on zero inputs) the capacity-shaped
        executable(s) without touching the cache or the request
        counters — the Server.warmup_async path."""
        import jax
        for bk in ((bucket,) if bucket else self.buckets):
            slots = _np.zeros((bk, self.max_ids), _np.int32)
            lengths = _np.zeros((bk,), _np.int32)
            fn = self._jit(bk)
            self.cache.buf, s, i = fn(self.cache.buf, self.corpus,
                                      slots, lengths)
            jax.block_until_ready((s, i))

    def _plan(self, id_lists):
        """Host-side batch plan: clip/truncate each request to max_ids,
        make every needed row device-resident, return the slot matrix +
        lengths (+ the real gather count billed to admission)."""
        b = len(id_lists)
        slots = _np.zeros((b, self.max_ids), dtype=_np.int32)
        lengths = _np.zeros((b,), dtype=_np.int32)
        flat = []
        for ids in id_lists:
            ids = list(ids)[:self.max_ids]
            flat.extend(ids)
        all_slots = (self.cache.ensure(_np.asarray(flat, _np.int64))
                     if flat else _np.zeros((0,), _np.int32))
        off = 0
        for j, ids in enumerate(id_lists):
            n = min(len(ids), self.max_ids)
            lengths[j] = n
            slots[j, :n] = all_slots[off:off + n]
            off += n
        return slots, lengths, len(flat)

    def recommend_batch(self, id_lists, bucket=None):
        """Score a batch of ragged id lists; returns (scores, item_ids)
        as host arrays, one row per request. ONE device dispatch and
        ONE d2h for the whole batch (PR-3 discipline)."""
        import jax
        from .. import profiler
        b = len(id_lists)
        if bucket is None:
            bucket = next((bk for bk in self.buckets if bk >= b),
                          self.buckets[-1])
        if b > bucket:
            raise MXNetError(
                "recommend: batch of %d exceeds bucket %d" % (b, bucket))
        slots, lengths, gathers = self._plan(id_lists)
        if b < bucket:
            slots = _np.concatenate(
                [slots, _np.zeros((bucket - b, self.max_ids),
                                  _np.int32)])
            lengths = _np.concatenate(
                [lengths, _np.zeros((bucket - b,), _np.int32)])
        fn = self._jit(bucket)
        self.cache.buf, top_s, top_i = fn(self.cache.buf, self.corpus,
                                          slots, lengths)
        host = jax.device_get((top_s, top_i))
        nbytes = sum(h.nbytes for h in host)
        profiler.record_host_sync("d2h", nbytes)
        self.requests += b
        self.gathers += gathers
        return _np.asarray(host[0])[:b], _np.asarray(host[1])[:b]

    # -- cost model ----------------------------------------------------------
    def gather_unit_s(self, device_kind=None):
        """Roofline seconds per single gather unit — the admission
        queue's billing rate (load_s = pending gathers x this)."""
        from .. import perfmodel
        if device_kind is None:
            try:
                import jax
                device_kind = jax.devices()[0].device_kind
            except Exception:
                device_kind = perfmodel.DEFAULT_DEVICE_KIND
        base = perfmodel.recommend_request_seconds(
            1, self.dim, self.items,
            dtype_bytes=self.cache.dtype.itemsize,
            device_kind=device_kind)
        return max(base, 1e-9)

    def estimate_request_s(self, gathers, device_kind=None):
        from .. import perfmodel
        if device_kind is None:
            try:
                import jax
                device_kind = jax.devices()[0].device_kind
            except Exception:
                device_kind = perfmodel.DEFAULT_DEVICE_KIND
        return perfmodel.recommend_request_seconds(
            gathers, self.dim, self.items,
            dtype_bytes=self.cache.dtype.itemsize,
            device_kind=device_kind)

    # -- discipline ----------------------------------------------------------
    def lookup_lowering_text(self, bucket=None):
        """StableHLO of the served lookup program, chip-free
        (JAX_PLATFORMS=cpu) — MXL511's input."""
        import jax
        bucket = bucket or self.buckets[0]
        shapes = (
            jax.ShapeDtypeStruct((self.cache.capacity, self.dim),
                                 self.cache.dtype),
            jax.ShapeDtypeStruct((self.items, self.dim),
                                 self.corpus.dtype),
            jax.ShapeDtypeStruct((bucket, self.max_ids), _np.int32),
            jax.ShapeDtypeStruct((bucket,), _np.int32),
        )
        return self._jit(bucket).lower(*shapes).as_text()

    def check_discipline(self, bucket=None):
        """Run mxlint MXL511 over the served lookup lowering; returns
        the diagnostics list ([] = clean)."""
        from ..analysis import hlo_passes
        text = self.lookup_lowering_text(bucket)
        return hlo_passes.embedding_lookup_discipline_pass(
            text, "recommend/lookup", cache_params=(0,))

    def stats(self):
        """Host-held snapshot (cache counters + request accounting)."""
        out = self.cache.stats()
        out.update(requests=self.requests, gathers=self.gathers,
                   corpus_rows=self.items, max_ids=self.max_ids,
                   k=self.k, buckets=list(self.buckets))
        return out
