"""Automatic symbol naming (parity: python/mxnet/name.py — NameManager
:25, Prefix :93).

``with mx.name.Prefix("layer1_"):`` prefixes every auto-generated symbol
name created in scope; a plain ``NameManager`` gives a fresh counter
namespace. The symbolic layer's auto-namer consults the active manager
(symbol/symbol.py _auto_name)."""
import threading

__all__ = ["NameManager", "Prefix", "current"]

_current = threading.local()


class NameManager:
    """Thread-scoped auto-namer: ``get(name, hint)`` returns the user
    name unchanged, else ``hint%d`` with a per-hint counter."""

    def __init__(self):
        self._counter = {}
        self._old = None

    def get(self, name, hint):
        if name:
            return name
        c = self._counter.get(hint, 0)
        self._counter[hint] = c + 1
        return "%s%d" % (hint, c)

    def __enter__(self):
        self._old = current()
        _current.value = self
        return self

    def __exit__(self, *exc):
        _current.value = self._old


class Prefix(NameManager):
    """Auto-names get a fixed prefix inside the scope."""

    def __init__(self, prefix):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        name = super().get(name, hint)
        return self._prefix + name


def current():
    """The active manager (a default one is created per thread)."""
    if not hasattr(_current, "value"):
        _current.value = NameManager()
    return _current.value
