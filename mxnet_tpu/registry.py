"""Generic class registries (parity: python/mxnet/registry.py —
get_register_func :49, get_alias_func :88, get_create_func :115).

The factory trio behind the reference's optimizer/initializer/metric
registries, exposed so user extensions can build the same pattern:

    register = mx.registry.get_register_func(MyBase, "mything")
    create = mx.registry.get_create_func(MyBase, "mything")

``create`` accepts a name, an instance (returned as-is), a config dict,
or the reference's JSON string forms ('["name", {...}]' / '{...}').
"""
import json
import warnings

_REGISTRY = {}

__all__ = ["get_register_func", "get_alias_func", "get_create_func"]


def get_register_func(base_class, nickname):
    """Build a @register decorator for subclasses of ``base_class``."""
    registry = _REGISTRY.setdefault(base_class, {})

    def register(klass, name=None):
        assert issubclass(klass, base_class), \
            "Can only register subclass of %s" % base_class.__name__
        name = (name or klass.__name__).lower()
        if name in registry:
            warnings.warn(
                "New %s %s.%s registered with name %s is overriding "
                "existing %s %s.%s" % (
                    nickname, klass.__module__, klass.__name__, name,
                    nickname, registry[name].__module__,
                    registry[name].__name__))
        registry[name] = klass
        return klass

    register.__doc__ = "Register %s to the %s factory" % (
        base_class.__name__, nickname)
    return register


def get_alias_func(base_class, nickname):
    """Build an @alias("name", ...) decorator for ``base_class``."""
    register = get_register_func(base_class, nickname)

    def alias(*aliases):
        def reg(klass):
            for name in aliases:
                register(klass, name)
            return klass
        return reg
    return alias


def get_create_func(base_class, nickname):
    """Build a create(name_or_instance_or_config, **kwargs) factory."""
    registry = _REGISTRY.setdefault(base_class, {})

    def create(*args, **kwargs):
        if args:
            name, args = args[0], args[1:]
        else:
            name = kwargs.pop(nickname)
        if isinstance(name, base_class):
            assert not args and not kwargs, \
                "%s is already an instance; extra arguments are invalid" \
                % nickname
            return name
        if isinstance(name, dict):
            return create(**name)
        assert isinstance(name, str), "%s must be a string" % nickname
        if name.startswith("["):
            assert not args and not kwargs
            name, kwargs = json.loads(name)
            return create(name, **kwargs)
        if name.startswith("{"):
            assert not args and not kwargs
            return create(**json.loads(name))
        name = name.lower()
        assert name in registry, \
            "%s is not registered. Please register with %s.register first" \
            % (name, nickname)
        return registry[name](*args, **kwargs)

    create.__doc__ = ("Create a %s instance by name, instance, config "
                      "dict, or JSON string." % nickname)
    return create
