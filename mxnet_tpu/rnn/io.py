"""Bucketed sequence iterator (parity: python/mxnet/rnn/io.py
BucketSentenceIter :33-211) — groups variable-length sentences into
buckets so each bucket compiles one static-shape program (the TPU-native
reason to keep bucketing: XLA recompiles per shape, so buckets bound the
number of compilations exactly like the reference bounds cuDNN plans)."""
from __future__ import annotations

import logging
import random as _pyrandom

import numpy as _np

from ..io.io import DataBatch, DataDesc
from ..ndarray import ndarray as _nd

__all__ = ["BucketSentenceIter"]


class BucketSentenceIter:
    def __init__(self, sentences, batch_size, buckets=None, invalid_label=-1,
                 data_name="data", label_name="softmax_label", dtype="float32",
                 layout="NT"):
        if not buckets:
            lens = _np.bincount([len(s) for s in sentences])
            buckets = [i for i, n in enumerate(lens)
                       if n >= batch_size]
        buckets.sort()
        self.data = [[] for _ in buckets]
        ndiscard = 0
        for sent in sentences:
            buck = _np.searchsorted(buckets, len(sent))
            if buck == len(buckets):
                ndiscard += 1
                continue
            buff = _np.full((buckets[buck],), invalid_label, dtype=dtype)
            buff[:len(sent)] = sent
            self.data[buck].append(buff)
        if ndiscard:
            logging.warning("discarded %d sentences longer than the largest "
                            "bucket", ndiscard)
        self.ndiscard = ndiscard
        # explicit 2-D shape: a bucket with zero sentences must still be
        # (0, bucket_len), not a 1-D empty array
        self.data = [_np.asarray(x, dtype=dtype).reshape(-1, blen)
                     for x, blen in zip(self.data, buckets)]
        self.batch_size = batch_size
        self.buckets = buckets
        self.data_name = data_name
        self.label_name = label_name
        self.invalid_label = invalid_label
        self.nddata = []
        self.ndlabel = []
        self.major_axis = layout.find("N")
        self.layout = layout
        self.default_bucket_key = max(buckets)

        if self.major_axis == 0:
            self.provide_data = [DataDesc(
                data_name, (batch_size, self.default_bucket_key),
                layout=layout)]
            self.provide_label = [DataDesc(
                label_name, (batch_size, self.default_bucket_key),
                layout=layout)]
        else:
            self.provide_data = [DataDesc(
                data_name, (self.default_bucket_key, batch_size),
                layout=layout)]
            self.provide_label = [DataDesc(
                label_name, (self.default_bucket_key, batch_size),
                layout=layout)]
        self.idx = []
        for i, buck in enumerate(self.data):
            self.idx.extend([(i, j) for j in
                             range(0, len(buck) - batch_size + 1,
                                   batch_size)])
        self.curr_idx = 0
        self.reset()

    def reset(self):
        self.curr_idx = 0
        _pyrandom.shuffle(self.idx)
        for buck in self.data:
            _np.random.shuffle(buck)
        self.nddata = []
        self.ndlabel = []
        for buck in self.data:
            # label = next-token shift (reference io.py:185)
            label = _np.empty_like(buck)
            label[:, :-1] = buck[:, 1:]
            label[:, -1] = self.invalid_label
            self.nddata.append(_nd.array(buck))
            self.ndlabel.append(_nd.array(label))

    def __iter__(self):
        return self

    def __next__(self):
        return self.next()

    def next(self):
        if self.curr_idx == len(self.idx):
            raise StopIteration
        i, j = self.idx[self.curr_idx]
        self.curr_idx += 1
        if self.major_axis == 1:
            data = self.nddata[i][j:j + self.batch_size].T
            label = self.ndlabel[i][j:j + self.batch_size].T
        else:
            data = self.nddata[i][j:j + self.batch_size]
            label = self.ndlabel[i][j:j + self.batch_size]
        return DataBatch(
            [data], [label], pad=0, bucket_key=self.buckets[i],
            provide_data=[DataDesc(self.data_name, data.shape,
                                   layout=self.layout)],
            provide_label=[DataDesc(self.label_name, label.shape,
                                    layout=self.layout)])
