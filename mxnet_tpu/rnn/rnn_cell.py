"""Symbolic RNN cells (``mx.rnn``).

Parity surface: ``python/mxnet/rnn/rnn_cell.py`` (BaseRNNCell :121,
RNNCell :341, LSTMCell :396, GRUCell :476, FusedRNNCell :543,
SequentialRNNCell :756, BidirectionalCell :830, DropoutCell). These build
SYMBOL graphs; gluon.rnn covers the imperative side. The v0.x bucketing
examples (lstm_bucketing.py etc.) drive this API.

TPU notes: an unrolled cell graph compiles into one XLA program at bind
time (per-timestep FullyConnected ops fuse into MXU matmul chains);
FusedRNNCell routes to the lax.scan-based fused RNN operator — prefer it
for long sequences (compile time stays flat).
"""
from __future__ import annotations

from .. import symbol as _sym

__all__ = ["RNNParams", "BaseRNNCell", "RNNCell", "LSTMCell", "GRUCell",
           "FusedRNNCell", "SequentialRNNCell", "BidirectionalCell",
           "DropoutCell"]


class RNNParams:
    """Container for cell parameter symbols, shared by name (reference
    rnn_cell.py:95)."""

    def __init__(self, prefix=""):
        self._prefix = prefix
        self._params = {}

    def get(self, name, **kwargs):
        name = self._prefix + name
        if name not in self._params:
            self._params[name] = _sym.Variable(name, **kwargs)
        return self._params[name]


class BaseRNNCell:
    """Abstract cell: ``cell(inputs, states) -> (output, states)`` over
    symbols, plus ``unroll`` (reference rnn_cell.py:121)."""

    def __init__(self, prefix="", params=None):
        if params is None:
            params = RNNParams(prefix)
            self._own_params = True
        else:
            self._own_params = False
        self._prefix = prefix
        self._params = params
        self._init_counter = -1
        self._counter = -1

    @property
    def params(self):
        self._own_params = False
        return self._params

    @property
    def state_info(self):
        raise NotImplementedError

    @property
    def _gate_names(self):
        return ()

    def __call__(self, inputs, states):
        raise NotImplementedError

    def reset(self):
        self._init_counter = -1
        self._counter = -1

    def begin_state(self, func=None, **kwargs):
        """Initial state symbols. Default: named Variables — simple_bind
        allocates them zero-filled, which reproduces the reference's
        zero initial state; pass shapes at bind time for inference.
        (unroll with begin_state=None instead derives zero states from
        the input symbol, so no extra bind args are needed.)"""
        states = []
        for info in self.state_info:
            self._init_counter += 1
            if func is None:
                state = _sym.Variable(
                    "%sbegin_state_%d" % (self._prefix, self._init_counter),
                    **kwargs)
            else:
                state = func(
                    name="%sbegin_state_%d" % (self._prefix,
                                               self._init_counter),
                    **{**info, **kwargs})
            states.append(state)
        return states

    def _zero_state_from(self, ref, batch_axis=0):
        """Zero states shaped off a reference symbol's batch dim — shape
        inference flows forward, unlike free begin-state Variables."""
        return [_sym._rnn_begin_state(ref, state_shape=info["shape"],
                                      batch_axis=batch_axis)
                for info in self.state_info]

    def unpack_weights(self, args):
        return dict(args)

    def pack_weights(self, args):
        return dict(args)

    @staticmethod
    def _default_inputs(length, input_prefix):
        """Per-step named placeholders for ``unroll(inputs=None)`` — the one
        place the naming contract lives."""
        return [_sym.Variable("%st%d_data" % (input_prefix, i))
                for i in range(length)]

    def unroll(self, length, inputs=None, begin_state=None, input_prefix="",
               layout="NTC", merge_outputs=None):
        """Unroll for `length` steps (reference rnn_cell.py:254)."""
        self.reset()
        axis = layout.find("T")
        if inputs is None:
            inputs = self._default_inputs(length, input_prefix)
        elif not isinstance(inputs, (list, tuple)):
            inputs = list(_sym.SliceChannel(inputs, num_outputs=length,
                                            axis=axis, squeeze_axis=1))
        if begin_state is None:
            begin_state = self._zero_state_from(inputs[0])
        states = begin_state
        outputs = []
        for i in range(length):
            out, states = self(inputs[i], states)
            outputs.append(out)
        if merge_outputs:
            outputs = [_sym.expand_dims(o, axis=axis) for o in outputs]
            outputs = _sym.Concat(*outputs, dim=axis)
        return outputs, states


class RNNCell(BaseRNNCell):
    """Vanilla RNN cell (reference rnn_cell.py:341)."""

    def __init__(self, num_hidden, activation="tanh", prefix="rnn_",
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._activation = activation
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("",)

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h = _sym.FullyConnected(inputs, weight=self._iW, bias=self._iB,
                                  num_hidden=self._num_hidden,
                                  name="%si2h" % name)
        h2h = _sym.FullyConnected(states[0], weight=self._hW, bias=self._hB,
                                  num_hidden=self._num_hidden,
                                  name="%sh2h" % name)
        output = _sym.Activation(i2h + h2h, act_type=self._activation,
                                 name="%sout" % name)
        return output, [output]


class LSTMCell(BaseRNNCell):
    """LSTM cell (reference rnn_cell.py:396; gate order i,f,c,o)."""

    def __init__(self, num_hidden, prefix="lstm_", params=None,
                 forget_bias=1.0):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        from ..initializer import LSTMBias
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        # forget gate starts open (reference rnn_cell.py:396 LSTMBias)
        self._hB = self.params.get("h2h_bias",
                                   init=LSTMBias(forget_bias=forget_bias))
        self._forget_bias = forget_bias

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"},
                {"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("_i", "_f", "_c", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h = _sym.FullyConnected(inputs, weight=self._iW, bias=self._iB,
                                  num_hidden=self._num_hidden * 4,
                                  name="%si2h" % name)
        h2h = _sym.FullyConnected(states[0], weight=self._hW, bias=self._hB,
                                  num_hidden=self._num_hidden * 4,
                                  name="%sh2h" % name)
        gates = i2h + h2h
        sliced = list(_sym.SliceChannel(gates, num_outputs=4, axis=1,
                                        name="%sslice" % name))
        in_gate = _sym.Activation(sliced[0], act_type="sigmoid")
        forget_gate = _sym.Activation(sliced[1], act_type="sigmoid")
        in_transform = _sym.Activation(sliced[2], act_type="tanh")
        out_gate = _sym.Activation(sliced[3], act_type="sigmoid")
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * _sym.Activation(next_c, act_type="tanh")
        return next_h, [next_h, next_c]


class GRUCell(BaseRNNCell):
    """GRU cell (reference rnn_cell.py:476; gate order r,z,n)."""

    def __init__(self, num_hidden, prefix="gru_", params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("_r", "_z", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        prev = states[0]
        i2h = _sym.FullyConnected(inputs, weight=self._iW, bias=self._iB,
                                  num_hidden=self._num_hidden * 3,
                                  name="%si2h" % name)
        h2h = _sym.FullyConnected(prev, weight=self._hW, bias=self._hB,
                                  num_hidden=self._num_hidden * 3,
                                  name="%sh2h" % name)
        i2h_r, i2h_z, i2h_n = list(_sym.SliceChannel(
            i2h, num_outputs=3, axis=1, name="%si2h_slice" % name))
        h2h_r, h2h_z, h2h_n = list(_sym.SliceChannel(
            h2h, num_outputs=3, axis=1, name="%sh2h_slice" % name))
        reset = _sym.Activation(i2h_r + h2h_r, act_type="sigmoid")
        update = _sym.Activation(i2h_z + h2h_z, act_type="sigmoid")
        next_h_tmp = _sym.Activation(i2h_n + reset * h2h_n, act_type="tanh")
        next_h = (1.0 - update) * next_h_tmp + update * prev
        return next_h, [next_h]


class FusedRNNCell(BaseRNNCell):
    """Fused multi-layer RNN over the RNN operator (reference
    rnn_cell.py:543 — cuDNN there, lax.scan here). Parameters live in one
    packed vector like the reference."""

    def __init__(self, num_hidden, num_layers=1, mode="lstm",
                 bidirectional=False, dropout=0.0, get_next_state=False,
                 prefix=None, params=None, forget_bias=1.0):
        if prefix is None:
            prefix = "%s_" % mode
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._dropout = dropout
        self._get_next_state = get_next_state
        self._forget_bias = forget_bias
        # the packed vector carries a FusedRNN default initializer attr so
        # Module.init_params with ANY global initializer unpacks, inits
        # per-gate, and repacks (reference rnn_cell.py:578-580)
        from .. import initializer as _init
        self._param = self.params.get(
            "parameters",
            init=_init.FusedRNN(None, num_hidden, num_layers, mode,
                                bidirectional, forget_bias))

    @property
    def _gate_names(self):
        return {"rnn_relu": [""], "rnn_tanh": [""],
                "lstm": ["_i", "_f", "_c", "_o"],
                "gru": ["_r", "_z", "_o"]}[self._mode]

    @property
    def _directions(self):
        return ["l", "r"] if self._bidirectional else ["l"]

    def _slice_weights(self, flat, num_input, lh):
        """name -> (offset, shape) map over the packed vector, derived by
        walking ops/nn.py ``_rnn_param_shapes`` — the SAME layout the RNN
        operator unpacks at execution time, so the naming layer can never
        desync from the compute layer. Each gate-stacked block is split
        into per-gate reference names (``{prefix}{dir}{layer}_i2h{gate}_
        weight`` etc., reference rnn_cell.py:600)."""
        from ..ops.nn import _rnn_param_shapes
        gate_names = self._gate_names
        dirs = self._directions
        m = len(gate_names)
        shapes = _rnn_param_shapes(self._mode, num_input, lh,
                                   self._num_layers, self._bidirectional)
        group = {"wx": ("i2h", "weight"), "wh": ("h2h", "weight"),
                 "bx": ("i2h", "bias"), "bh": ("h2h", "bias")}
        spans = {}
        p = 0
        pair = 0    # (layer, direction) index; advances after each h-block
        for kind, shape in shapes:
            layer, d = divmod(pair % (self._num_layers * len(dirs)),
                              len(dirs))
            grp, suffix = group[kind]
            gshape = (lh,) if suffix == "bias" else (lh, shape[-1])
            per = 1
            for s in gshape:
                per *= s
            for gate in gate_names:
                name = "%s%s%d_%s%s_%s" % (self._prefix, dirs[d], layer,
                                           grp, gate, suffix)
                spans[name] = (p, gshape)
                p += per
            if kind in ("wh", "bh"):
                pair += 1
        assert p == flat.size, \
            "Invalid parameters size for FusedRNNCell: %d != %d" % (
                flat.size, p)
        return spans

    def unpack_weights(self, args):
        """Split the packed vector into named per-gate i2h/h2h weights
        and biases (reference rnn_cell.py:639)."""
        from .. import ndarray as _ndm
        import numpy as _np
        args = dict(args)
        arr = args.pop(self._param.name)
        flat = arr.asnumpy().reshape(-1)
        b = len(self._directions)
        m = len(self._gate_names)
        h = self._num_hidden
        num_input = flat.size // b // h // m \
            - (self._num_layers - 1) * (h + b * h + 2) - h - 2
        for name, (p, shape) in self._slice_weights(flat, num_input,
                                                    h).items():
            n = int(_np.prod(shape))
            args[name] = _ndm.array(flat[p:p + n].reshape(shape),
                                    ctx=arr.context)
        return args

    def pack_weights(self, args):
        """Inverse of unpack_weights (reference rnn_cell.py:652)."""
        from .. import ndarray as _ndm
        import numpy as _np
        args = dict(args)
        c0 = "%sl0_i2h%s_weight" % (self._prefix, self._gate_names[0])
        w0 = args[c0]
        num_input = w0.shape[1]
        b = len(self._directions)
        m = len(self._gate_names)
        h = self._num_hidden
        total = (num_input + h + 2) * h * m * b \
            + (self._num_layers - 1) * m * h * (h + b * h + 2) * b
        flat = _np.zeros((total,), dtype=_np.dtype(w0.dtype))
        for name, (p, shape) in self._slice_weights(flat, num_input,
                                                    h).items():
            n = int(_np.prod(shape))
            flat[p:p + n] = args.pop(name).asnumpy().reshape(-1)
        args[self._param.name] = _ndm.array(flat, ctx=w0.context)
        return args

    @property
    def state_info(self):
        b = self._num_layers * (2 if self._bidirectional else 1)
        info = [{"shape": (b, 0, self._num_hidden), "__layout__": "LNC"}]
        if self._mode == "lstm":
            info.append({"shape": (b, 0, self._num_hidden),
                         "__layout__": "LNC"})
        return info

    def unroll(self, length, inputs=None, begin_state=None, input_prefix="",
               layout="NTC", merge_outputs=None):
        self.reset()
        axis = layout.find("T")
        if inputs is None:
            inputs = self._default_inputs(length, input_prefix)
        if isinstance(inputs, (list, tuple)):
            inputs = _sym.Concat(*[_sym.expand_dims(i, axis=0)
                                   for i in inputs], dim=0)  # (T, N, C)
        else:
            if layout == "NTC":
                inputs = _sym.transpose(inputs, axes=(1, 0, 2))
        if begin_state is None:
            begin_state = self._zero_state_from(inputs, batch_axis=1)
        states = list(begin_state)
        state = states[0]
        state_cell = states[1] if self._mode == "lstm" else None
        args = [inputs, self._param, state]
        if state_cell is not None:
            args.append(state_cell)
        outs = _sym.RNN(*args, state_size=self._num_hidden,
                        num_layers=self._num_layers, mode=self._mode,
                        bidirectional=self._bidirectional, p=self._dropout,
                        state_outputs=self._get_next_state,
                        name="%srnn" % self._prefix)
        if self._get_next_state:
            outs = list(outs)
            output, states = outs[0], outs[1:]
        else:
            output, states = outs, []
        if layout == "NTC":
            output = _sym.transpose(output, axes=(1, 0, 2))
        if merge_outputs is False:
            output = list(_sym.SliceChannel(output, num_outputs=length,
                                            axis=axis, squeeze_axis=1))
        return output, states


class SequentialRNNCell(BaseRNNCell):
    """Stack of cells applied in sequence (reference rnn_cell.py:756)."""

    def __init__(self, params=None):
        super().__init__(prefix="", params=params)
        self._cells = []

    def add(self, cell):
        self._cells.append(cell)

    @property
    def state_info(self):
        return sum((c.state_info for c in self._cells), [])

    def begin_state(self, **kwargs):
        return sum((c.begin_state(**kwargs) for c in self._cells), [])

    def __call__(self, inputs, states):
        next_states = []
        p = 0
        for cell in self._cells:
            n = len(cell.state_info)
            out, st = cell(inputs, states[p:p + n])
            inputs = out
            next_states.extend(st)
            p += n
        return inputs, next_states

    def reset(self):
        super().reset()
        for c in self._cells:
            c.reset()


class BidirectionalCell(BaseRNNCell):
    """Forward + backward cells over the sequence (reference
    rnn_cell.py:830)."""

    def __init__(self, l_cell, r_cell, params=None, output_prefix="bi_"):
        super().__init__(prefix="", params=params)
        self._l_cell = l_cell
        self._r_cell = r_cell
        self._output_prefix = output_prefix

    @property
    def state_info(self):
        return self._l_cell.state_info + self._r_cell.state_info

    def begin_state(self, **kwargs):
        return (self._l_cell.begin_state(**kwargs)
                + self._r_cell.begin_state(**kwargs))

    def unroll(self, length, inputs=None, begin_state=None, input_prefix="",
               layout="NTC", merge_outputs=None):
        self.reset()
        axis = layout.find("T")
        if inputs is None:
            inputs = self._default_inputs(length, input_prefix)
        elif not isinstance(inputs, (list, tuple)):
            inputs = list(_sym.SliceChannel(inputs, num_outputs=length,
                                            axis=axis, squeeze_axis=1))
        if begin_state is None:
            begin_state = (self._l_cell._zero_state_from(inputs[0])
                           + self._r_cell._zero_state_from(inputs[0]))
        nl = len(self._l_cell.state_info)
        l_out, l_states = self._l_cell.unroll(
            length, inputs=list(inputs), begin_state=begin_state[:nl],
            layout=layout, merge_outputs=False)
        r_out, r_states = self._r_cell.unroll(
            length, inputs=list(reversed(inputs)),
            begin_state=begin_state[nl:], layout=layout,
            merge_outputs=False)
        outputs = [
            _sym.Concat(l, r, dim=1,
                        name="%st%d" % (self._output_prefix, i))
            for i, (l, r) in enumerate(zip(l_out, reversed(r_out)))]
        if merge_outputs:
            outputs = [_sym.expand_dims(o, axis=axis) for o in outputs]
            outputs = _sym.Concat(*outputs, dim=axis)
        return outputs, l_states + r_states

    def reset(self):
        super().reset()
        self._l_cell.reset()
        self._r_cell.reset()


class DropoutCell(BaseRNNCell):
    """Applies dropout to inputs (reference rnn_cell.py:710)."""

    def __init__(self, dropout, prefix="dropout_", params=None):
        super().__init__(prefix=prefix, params=params)
        self._dropout = dropout

    @property
    def state_info(self):
        return []

    def __call__(self, inputs, states):
        if self._dropout > 0:
            inputs = _sym.Dropout(inputs, p=self._dropout)
        return inputs, states
