"""Symbolic RNN package (parity: python/mxnet/rnn/)."""
from .rnn_cell import (RNNParams, BaseRNNCell, RNNCell, LSTMCell, GRUCell,
                       FusedRNNCell, SequentialRNNCell, BidirectionalCell,
                       DropoutCell)
from .io import BucketSentenceIter
