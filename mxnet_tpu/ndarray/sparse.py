"""Sparse NDArrays: RowSparse and CSR.

Parity: python/mxnet/ndarray/sparse.py + src/operator/tensor/cast_storage /
dot-inl.h sparse kernels (storage types enum include/mxnet/ndarray.h:61-65).

TPU-native design (SURVEY.md §7 hard-part 2): there is no sparse HLO; we keep
the *storage format* (indices+values / indptr+indices+data as dense jax
arrays — static shapes, MXU-friendly segment ops) and lower sparse compute to
gather/scatter/segment-sum, which XLA maps well to TPU. Row-sparse is the
format that matters in practice (embedding grads, optimizer lazy updates) and
it round-trips exactly. `nnz`-dependent shapes are materialized eagerly
(host-side), matching the reference's eager cast_storage semantics.

What executes SPARSE (never touching the dense logical shape):

* ``dot(csr, dense)`` / ``dot(csr.T, dense)`` — gather + scatter-add over
  nnz (reference src/operator/tensor/dot-inl.h);
* ``retain`` — sorted search over stored indices;
* row-sparse ``add`` (the kvstore reduce) — index-union on host (indices
  are tiny), values segment-summed on device;
* lazy optimizer updates (SGD/Adam/AdaGrad in optimizer.py) — only the
  gradient's stored rows are gathered, updated and scattered back
  (reference src/operator/optimizer_op-inl.h row_sparse kernels);
* kvstore ``row_sparse_pull`` — retain over the stored value.

Everything else falls back to dense via ``todense()`` — the reference's
storage-fallback behavior, chosen deliberately: on TPU a dense masked op
over a static shape usually beats a dynamic-shaped "sparse" one unless nnz
is tiny. (v5p+ SparseCore embeddings would slot in behind this same API;
not targeted while the bench chip is v5e.)
"""
from __future__ import annotations

import numpy as _np
import jax
import jax.numpy as jnp

from ..context import current_context
from ..base import normalize_dtype
from . import ndarray as _ndarray
from .ndarray import NDArray

__all__ = ["RowSparseNDArray", "CSRNDArray", "csr_matrix", "row_sparse_array",
           "cast_storage", "dot", "zeros", "retain"]


class BaseSparseNDArray(NDArray):
    """Shared base: shadows the dense `_data` slot with a lazily-materialized
    dense view so every inherited NDArray method (arithmetic, size, copy,
    astype, ...) works on sparse inputs by falling back to dense — the
    reference's storage-fallback behavior (src/common/exec_utils.h)."""

    __slots__ = ("_dense_cache",)

    @property
    def _data(self):
        if self._dense_cache is None:
            self._dense_cache = self._make_dense()
        return self._dense_cache

    def _invalidate(self):
        self._dense_cache = None

    @property
    def size(self):
        n = 1
        for s in self._shape:
            n *= s
        return n

    @property
    def ndim(self):
        return len(self._shape)


class RowSparseNDArray(BaseSparseNDArray):
    """row_sparse: (indices[i] -> data[i, :]) pairs + dense logical shape."""

    __slots__ = ("_indices", "_values", "_shape")

    def __init__(self, data, indices, shape, ctx=None):
        self._values = data if not isinstance(data, NDArray) else data._data
        self._indices = (indices if not isinstance(indices, NDArray)
                         else indices._data).astype(jnp.int64)
        self._shape = tuple(shape)
        self._ctx = ctx or current_context()
        self._ag = None
        self._version = 0
        self._dense_cache = None

    def _make_dense(self):
        dense = jnp.zeros(self._shape, self._values.dtype)
        return dense.at[self._indices].set(self._values)

    # -- NDArray surface overrides -----------------------------------------
    @property
    def stype(self):
        return "row_sparse"

    @property
    def shape(self):
        return self._shape

    @property
    def dtype(self):
        return self._values.dtype

    @property
    def indices(self):
        return NDArray(self._indices, ctx=self._ctx)

    @property
    def data(self):
        return NDArray(self._values, ctx=self._ctx)

    def tostype(self, stype):
        if stype == "row_sparse":
            return self
        if stype == "default":
            return self.todense()
        raise ValueError("cast row_sparse -> %s not supported" % stype)

    def todense(self):
        return NDArray(self._data, ctx=self._ctx)

    def asnumpy(self):
        return self.todense().asnumpy()

    def copyto(self, other):
        if isinstance(other, RowSparseNDArray):
            other._indices = self._indices
            other._values = self._values
            other._shape = self._shape
            other._invalidate()
            return other
        return super().copyto(other)

    def wait_to_read(self):
        from .. import engine as _engine
        _engine.on_complete(self._values)

    def __repr__(self):
        return "\n<RowSparseNDArray %s @%s>" % (
            "x".join(str(s) for s in self._shape), self._ctx)

    def retain(self, row_ids):
        return retain(self, row_ids)

    def copy(self):
        # storage-preserving (NDArray.copy would densify); jnp arrays are
        # immutable so sharing them is a true copy
        return RowSparseNDArray(self._values, self._indices, self._shape,
                                ctx=self._ctx)


class CSRNDArray(BaseSparseNDArray):
    """Compressed sparse row matrix."""

    __slots__ = ("_indptr", "_indices", "_values", "_shape")

    def __init__(self, data, indptr, indices, shape, ctx=None):
        self._values = data if not isinstance(data, NDArray) else data._data
        self._indptr = (indptr if not isinstance(indptr, NDArray)
                        else indptr._data).astype(jnp.int64)
        self._indices = (indices if not isinstance(indices, NDArray)
                         else indices._data).astype(jnp.int64)
        self._shape = tuple(shape)
        self._ctx = ctx or current_context()
        self._ag = None
        self._version = 0
        self._dense_cache = None

    def _make_dense(self):
        rows = self._row_ids()
        dense = jnp.zeros(self._shape, self._values.dtype)
        return dense.at[rows, self._indices].set(self._values)

    @property
    def stype(self):
        return "csr"

    @property
    def shape(self):
        return self._shape

    @property
    def dtype(self):
        return self._values.dtype

    @property
    def indices(self):
        return NDArray(self._indices, ctx=self._ctx)

    @property
    def indptr(self):
        return NDArray(self._indptr, ctx=self._ctx)

    @property
    def data(self):
        return NDArray(self._values, ctx=self._ctx)

    def tostype(self, stype):
        if stype == "csr":
            return self
        if stype == "default":
            return self.todense()
        raise ValueError("cast csr -> %s not supported" % stype)

    def _row_ids(self):
        # expand indptr -> per-nnz row index
        counts = self._indptr[1:] - self._indptr[:-1]
        return jnp.repeat(jnp.arange(self._shape[0], dtype=jnp.int64), counts,
                          total_repeat_length=self._values.shape[0])

    def todense(self):
        return NDArray(self._data, ctx=self._ctx)

    def asnumpy(self):
        return self.todense().asnumpy()

    def wait_to_read(self):
        from .. import engine as _engine
        _engine.on_complete(self._values)

    def __getitem__(self, key):
        if isinstance(key, slice):
            # row slice: rebuild via dense for simplicity
            return cast_storage(NDArray(self.todense()._data[key], ctx=self._ctx), "csr")
        return self.todense()[key]

    def copy(self):
        return CSRNDArray(self._values, self._indptr, self._indices,
                          self._shape, ctx=self._ctx)

    def __repr__(self):
        return "\n<CSRNDArray %s @%s>" % (
            "x".join(str(s) for s in self._shape), self._ctx)


# ---------------------------------------------------------------------------
# constructors
# ---------------------------------------------------------------------------

def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        return CSRNDArray(jnp.asarray(_np.asarray(data), dtype=normalize_dtype(dtype)),
                          jnp.asarray(_np.asarray(indptr)),
                          jnp.asarray(_np.asarray(indices)), shape, ctx=ctx)
    # from dense
    dense = _np.asarray(arg1.asnumpy() if isinstance(arg1, NDArray) else arg1)
    return _csr_from_dense(dense, ctx)


def _csr_from_dense(dense_np, ctx=None):
    rows, cols = _np.nonzero(dense_np)
    vals = dense_np[rows, cols]
    indptr = _np.zeros(dense_np.shape[0] + 1, dtype=_np.int64)
    _np.add.at(indptr, rows + 1, 1)
    indptr = _np.cumsum(indptr)
    return CSRNDArray(jnp.asarray(vals), jnp.asarray(indptr),
                      jnp.asarray(cols.astype(_np.int64)), dense_np.shape, ctx=ctx)


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        data = _np.asarray(data) if not isinstance(data, NDArray) else data.asnumpy()
        indices = _np.asarray(indices) if not isinstance(indices, NDArray) else indices.asnumpy()
        return RowSparseNDArray(jnp.asarray(data, dtype=normalize_dtype(dtype)),
                                jnp.asarray(indices), shape, ctx=ctx)
    dense = _np.asarray(arg1.asnumpy() if isinstance(arg1, NDArray) else arg1)
    return _rsp_from_dense(dense, ctx)


def _rsp_from_dense(dense_np, ctx=None):
    nz_rows = _np.where(_np.any(dense_np != 0, axis=tuple(range(1, dense_np.ndim))))[0]
    return RowSparseNDArray(jnp.asarray(dense_np[nz_rows]),
                            jnp.asarray(nz_rows.astype(_np.int64)),
                            dense_np.shape, ctx=ctx)


def zeros(stype, shape, ctx=None, dtype=None):
    dt = normalize_dtype(dtype) or _np.float32
    if stype == "row_sparse":
        ncol = shape[1:] if len(shape) > 1 else ()
        return RowSparseNDArray(jnp.zeros((0,) + tuple(ncol), dt),
                                jnp.zeros((0,), jnp.int64), shape, ctx=ctx)
    if stype == "csr":
        return CSRNDArray(jnp.zeros((0,), dt), jnp.zeros((shape[0] + 1,), jnp.int64),
                          jnp.zeros((0,), jnp.int64), shape, ctx=ctx)
    return _ndarray.zeros(shape, ctx=ctx, dtype=dtype)


# ---------------------------------------------------------------------------
# ops (cast_storage / dot / retain / elemwise helpers)
# ---------------------------------------------------------------------------

def cast_storage(arr, stype):
    """reference: src/operator/tensor/cast_storage (dense<->sparse)."""
    if stype == arr.stype:
        return arr
    if stype == "default":
        return arr.todense()
    if stype == "row_sparse" and isinstance(arr, NDArray) \
            and not isinstance(arr, BaseSparseNDArray):
        # device fast path: the row-occupancy reduction runs ON DEVICE
        # and only an (N,) bool vector crosses to the host (picking the
        # row ids is inherently data-dependent); the kept rows are then
        # a device gather. The naive path copies the WHOLE dense matrix
        # to the host — for a large embedding gradient that is the
        # entire point of being sparse, gone.
        import jax.numpy as jnp
        g = arr._data
        occ = _np.asarray(jnp.any(g != 0, axis=tuple(range(1, g.ndim))))
        rows = _np.nonzero(occ)[0].astype(_np.int64)
        return RowSparseNDArray(g[jnp.asarray(rows)],
                                jnp.asarray(rows), arr.shape,
                                ctx=arr._ctx)
    dense_np = arr.asnumpy()
    if stype == "row_sparse":
        return _rsp_from_dense(dense_np, ctx=arr._ctx)
    if stype == "csr":
        if dense_np.ndim != 2:
            raise ValueError("csr requires 2-D")
        return _csr_from_dense(dense_np, ctx=arr._ctx)
    raise ValueError(stype)


def write_rows(rsp, rows, new_vals):
    """Overwrite/insert the given rows of a RowSparseNDArray in place,
    keeping it sparse (the reference dist-server row_sparse weight update,
    kvstore_dist_server.h:517-716). `rows` must be unique."""
    wi = _np.asarray(rsp.indices.asnumpy())
    ri = _np.asarray(rows)
    uniq = _np.unique(_np.concatenate([wi, ri]))
    cols = rsp.shape[1:]
    out = jnp.zeros((len(uniq),) + tuple(cols), rsp.dtype)
    if len(wi):
        out = out.at[jnp.asarray(_np.searchsorted(uniq, wi))].set(rsp._values)
    out = out.at[jnp.asarray(_np.searchsorted(uniq, ri))].set(
        jnp.asarray(new_vals, rsp.dtype))
    rsp._indices = jnp.asarray(uniq.astype(_np.int64))
    rsp._values = out
    rsp._invalidate()
    return rsp


def retain(rsp, row_ids):
    """sparse_retain: keep only requested rows (reference sparse_retain
    op). Executes sparse: a sorted-search over the stored indices (no
    dense materialization — O(nnz log nnz + |ids|) instead of O(size))."""
    ids = row_ids._data.astype(jnp.int64) if isinstance(row_ids, NDArray) \
        else jnp.asarray(_np.asarray(row_ids)).astype(jnp.int64)
    idx = rsp._indices
    vals = rsp._values
    if vals.shape[0] == 0:
        zeros_row = jnp.zeros((ids.shape[0],) + rsp.shape[1:], rsp.dtype)
        return RowSparseNDArray(zeros_row, ids, rsp.shape, ctx=rsp._ctx)
    order = jnp.argsort(idx)
    sidx, svals = idx[order], vals[order]
    pos = jnp.clip(jnp.searchsorted(sidx, ids), 0, sidx.shape[0] - 1)
    hit = sidx[pos] == ids
    picked = svals[pos]
    out_vals = jnp.where(
        hit.reshape((-1,) + (1,) * (picked.ndim - 1)), picked,
        jnp.zeros_like(picked))
    return RowSparseNDArray(out_vals, ids, rsp.shape, ctx=rsp._ctx)


def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """Sparse-aware dot: csr @ dense and csr.T @ dense.

    Lowered to segment-sum/gather — static shapes, TPU friendly.
    """
    if isinstance(lhs, CSRNDArray) and isinstance(rhs, NDArray) and not isinstance(rhs, BaseSparseNDArray):
        rows = lhs._row_ids()
        cols = lhs._indices
        vals = lhs._values
        d = rhs._data
        if transpose_a:
            # out[c, :] += vals * d[row, :]
            contrib = vals[:, None] * d[rows]
            out = jax.ops.segment_sum(contrib, cols, num_segments=lhs.shape[1]) \
                if hasattr(jax.ops, "segment_sum") else \
                jnp.zeros((lhs.shape[1], d.shape[1]), d.dtype).at[cols].add(contrib)
            return NDArray(out, ctx=lhs._ctx)
        contrib = vals[:, None] * d[cols]
        out = jnp.zeros((lhs.shape[0], d.shape[1]), d.dtype).at[rows].add(contrib)
        return NDArray(out, ctx=lhs._ctx)
    if isinstance(lhs, RowSparseNDArray):
        lhs = lhs.todense()
    if isinstance(rhs, BaseSparseNDArray):
        rhs = rhs.todense()
    return _ndarray.invoke("dot", [lhs, rhs],
                           {"transpose_a": transpose_a, "transpose_b": transpose_b})


def add(lhs, rhs):
    if isinstance(lhs, RowSparseNDArray) and isinstance(rhs, RowSparseNDArray):
        # union of stored rows, values segment-summed ON DEVICE: only the
        # (tiny) index vectors touch the host to fix the result nnz —
        # never the dense logical shape (kvstore reduce of embedding-table
        # grads must not allocate the table)
        li = _np.asarray(lhs.indices.asnumpy())
        ri = _np.asarray(rhs.indices.asnumpy())
        uniq, inv = _np.unique(_np.concatenate([li, ri]),
                               return_inverse=True)
        vals = jnp.concatenate([lhs._values, rhs._values])
        summed = jnp.zeros((len(uniq),) + vals.shape[1:], vals.dtype) \
            .at[jnp.asarray(inv)].add(vals)
        return RowSparseNDArray(summed, jnp.asarray(uniq.astype(_np.int64)),
                                lhs.shape, ctx=lhs._ctx)
    l = lhs.todense() if isinstance(lhs, BaseSparseNDArray) else lhs
    r = rhs.todense() if isinstance(rhs, BaseSparseNDArray) else rhs
    return l + r
