"""mx.nd.random namespace (parity: python/mxnet/ndarray/random.py)."""
from __future__ import annotations

from . import ndarray as _nd


def _shape(shape):
    if shape is None:
        return (1,)
    if isinstance(shape, int):
        return (shape,)
    return tuple(shape)


def uniform(low=0.0, high=1.0, shape=None, dtype="float32", ctx=None, out=None, **kw):
    if isinstance(low, _nd.NDArray):
        return _nd.invoke("_sample_uniform", [low, high], {"shape": shape or ()})
    return _nd.invoke("_random_uniform", [], {"low": low, "high": high,
                                              "shape": _shape(shape), "dtype": dtype, "ctx": ctx}, out=out)


def normal(loc=0.0, scale=1.0, shape=None, dtype="float32", ctx=None, out=None, **kw):
    if isinstance(loc, _nd.NDArray):
        return _nd.invoke("_sample_normal", [loc, scale], {"shape": shape or ()})
    return _nd.invoke("_random_normal", [], {"loc": loc, "scale": scale,
                                             "shape": _shape(shape), "dtype": dtype, "ctx": ctx}, out=out)




def gamma(alpha=1.0, beta=1.0, shape=None, dtype="float32", ctx=None, out=None, **kw):
    if isinstance(alpha, _nd.NDArray):
        return _nd.invoke("_sample_gamma", [alpha, beta], {"shape": shape or ()})
    return _nd.invoke("_random_gamma", [], {"alpha": alpha, "beta": beta,
                                            "shape": _shape(shape), "dtype": dtype, "ctx": ctx}, out=out)


def exponential(lam=1.0, shape=None, dtype="float32", ctx=None, out=None, **kw):
    return _nd.invoke("_random_exponential", [], {"lam": lam, "shape": _shape(shape),
                                                  "dtype": dtype, "ctx": ctx}, out=out)


def poisson(lam=1.0, shape=None, dtype="float32", ctx=None, out=None, **kw):
    return _nd.invoke("_random_poisson", [], {"lam": lam, "shape": _shape(shape),
                                              "dtype": dtype, "ctx": ctx}, out=out)


def negative_binomial(k=1, p=1.0, shape=None, dtype="float32", ctx=None, out=None, **kw):
    return _nd.invoke("_random_negative_binomial", [],
                      {"k": k, "p": p, "shape": _shape(shape), "dtype": dtype, "ctx": ctx}, out=out)


def generalized_negative_binomial(mu=1.0, alpha=1.0, shape=None, dtype="float32",
                                  ctx=None, out=None, **kw):
    return _nd.invoke("_random_generalized_negative_binomial", [],
                      {"mu": mu, "alpha": alpha, "shape": _shape(shape),
                       "dtype": dtype, "ctx": ctx}, out=out)


def randint(low, high, shape=None, dtype="int32", ctx=None, out=None, **kw):
    return _nd.invoke("_random_randint", [], {"low": low, "high": high,
                                              "shape": _shape(shape), "dtype": dtype, "ctx": ctx}, out=out)


def multinomial(data, shape=None, get_prob=False, dtype="int32", **kw):
    return _nd.invoke("_sample_multinomial", [data],
                      {"shape": shape or (), "get_prob": get_prob, "dtype": dtype})


def shuffle(data, **kw):
    return _nd.invoke("_shuffle", [data], {})


def randn(*shape, **kwargs):
    """Standard-normal draws with the shape given positionally
    (reference ndarray/random.py:155: randn(2, 3) == normal(0, 1, (2, 3)))."""
    loc = kwargs.pop("loc", 0.0)
    scale = kwargs.pop("scale", 1.0)
    return normal(loc, scale, shape or (1,), **kwargs)
