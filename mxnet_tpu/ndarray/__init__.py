"""Eager NDArray package (parity: python/mxnet/ndarray/)."""
from .ndarray import (NDArray, array, zeros, ones, full, arange, empty,
                      concat, invoke, waitall, save, load, moveaxis,
                      imperative_invoke, asnumpy_all)
from . import register as _register
from . import random
from . import contrib
from . import linalg
from . import sparse
from . import image
from . import op
from . import _internal
from .sparse import csr_matrix, row_sparse_array

_register.populate(__name__)

# `out=` capable aliases used across the reference codebase
zeros_like = globals().get("zeros_like")
ones_like = globals().get("ones_like")


