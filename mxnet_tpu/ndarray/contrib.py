"""Control-flow and contrib ndarray ops (parity:
python/mxnet/ndarray/contrib.py — foreach/while_loop/cond backed by
src/operator/control_flow.cc:1255/1316/1378 subgraph ops).

TPU-native design: in eager mode these run as Python control flow over
NDArrays (the reference's imperative semantics), fully differentiable
through the tape. When the inputs are raw jax values (inside a hybridized
trace), they lower to ``lax.scan`` / ``lax.while_loop`` / ``lax.cond`` so
compiled graphs get real XLA control flow — the design SURVEY.md §7
hard-part 4 calls for.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .ndarray import NDArray, invoke

__all__ = ["foreach", "while_loop", "cond", "isinf", "isnan",
           "isfinite", "edge_id", "dgl_adjacency", "dgl_subgraph",
           "dgl_csr_neighbor_uniform_sample",
           "dgl_csr_neighbor_non_uniform_sample", "getnnz"]


def _is_nd(x):
    if isinstance(x, NDArray):
        return True
    if isinstance(x, (list, tuple)):
        return any(_is_nd(v) for v in x)
    return False


def _is_jax_val(x):
    return isinstance(x, jax.Array) or isinstance(x, jax.core.Tracer)


def _has_tracer(x):
    if isinstance(x, jax.core.Tracer):
        return True
    if isinstance(x, (list, tuple)):
        return any(_has_tracer(v) for v in x)
    return False


def _check_not_mixed(*groups):
    """Inside a hybridized trace, NDArray constants can't cross into the
    jit program — fail with a clear message instead of a deep
    TracerBoolConversionError / leaked-tracer crash."""
    flat = []
    for g in groups:
        flat.extend(g if isinstance(g, (list, tuple)) else [g])
    if any(_has_tracer(v) for v in flat) and any(
            isinstance(v, NDArray) for v in flat):
        from ..base import MXNetError
        raise MXNetError(
            "control flow inside a hybridized forward mixes traced "
            "values with NDArray constants; create constants with F "
            "ops (or pass them as block parameters/inputs) so the whole "
            "loop stays inside the compiled program")


def _as_list(x):
    if isinstance(x, (list, tuple)):
        return list(x), False
    return [x], True


def foreach(body, data, init_states):
    """Run body over data slices along axis 0, threading states
    (reference contrib.foreach; symbolic analog `_foreach`
    control_flow.cc:1255)."""
    _check_not_mixed(data, init_states)
    if _is_nd(data) or _is_nd(init_states):
        return _foreach_eager(body, data, init_states)
    return _foreach_lax(body, data, init_states)


def _foreach_eager(body, data, init_states):
    data_list, single_data = _as_list(data)
    states, single_state = _as_list(init_states)
    n = data_list[0].shape[0]
    outputs = []
    single_out = True
    for i in range(n):
        eles = [d[i] for d in data_list]
        x = eles[0] if single_data else eles
        st = states[0] if single_state else states
        outs, new_st = body(x, st)
        states, _ = _as_list(new_st)
        outs, single_out = _as_list(outs)
        outputs.append(outs)
    stacked = [invoke("stack", [o[j] for o in outputs], {"axis": 0})
               for j in range(len(outputs[0]))]
    out = stacked[0] if single_out else stacked
    fin = states[0] if single_state else states
    return out, fin


def _foreach_lax(body, data, init_states):
    data_list, single_data = _as_list(data)
    states, single_state = _as_list(init_states)
    single_out = {}  # filled while tracing the first step

    def step(carry, xs):
        st = carry[0] if single_state else list(carry)
        x = xs[0] if single_data else list(xs)
        outs, new_st = body(x, st)
        new_st, _ = _as_list(new_st)
        outs, so = _as_list(outs)
        single_out["v"] = so
        return tuple(new_st), tuple(outs)

    final, ys = lax.scan(step, tuple(states), tuple(data_list))
    # unwrap by the body's actual output structure (same rule as the eager
    # path), not by element count
    out = ys[0] if single_out["v"] else list(ys)
    fin = final[0] if single_state else list(final)
    return out, fin


def while_loop(cond, func, loop_vars, max_iterations=None):
    """Run func while cond(loop_vars) holds, up to max_iterations; step
    outputs are stacked and padded to max_iterations (reference
    contrib.while_loop / `_while_loop` control_flow.cc:1316)."""
    if max_iterations is None:
        raise ValueError("max_iterations is required")
    _check_not_mixed(loop_vars)
    if _is_nd(loop_vars):
        return _while_eager(cond, func, loop_vars, max_iterations)
    return _while_lax(cond, func, loop_vars, max_iterations)


def _bool_of(x):
    if isinstance(x, NDArray):
        return bool(x.asscalar())
    return bool(x)


def _while_eager(cond, func, loop_vars, max_iterations):
    loop_vars, single = _as_list(loop_vars)
    steps = 0
    outputs = []
    out_fmt = None
    while steps < max_iterations and _bool_of(
            cond(*loop_vars)):
        step_out, loop_vars = func(*loop_vars)
        step_out, out_fmt_single = _as_list(step_out)
        out_fmt = out_fmt_single
        outputs.append(step_out)
        if not isinstance(loop_vars, (list, tuple)):
            loop_vars = [loop_vars]
        else:
            loop_vars = list(loop_vars)
        steps += 1
    if not outputs:
        # zero iterations: return zero-filled padded outputs, matching the
        # lax path's buffers; discover the step-output structure abstractly
        out_abs = jax.eval_shape(lambda *vs: func(*vs)[0],
                                 *[jnp.zeros(v.shape, v.dtype)
                                   for v in loop_vars])
        out_list, out_single = _as_list(out_abs)
        zeros = [NDArray(jnp.zeros((max_iterations,) + tuple(o.shape),
                                   o.dtype)) for o in out_list]
        out = zeros[0] if out_single else zeros
        fin = loop_vars[0] if single else loop_vars
        return out, fin
    # pad to max_iterations with zeros (reference semantics)
    stacked = []
    for j in range(len(outputs[0])):
        arr = invoke("stack", [o[j] for o in outputs], {"axis": 0})
        if steps < max_iterations:
            pad_shape = (max_iterations - steps,) + arr.shape[1:]
        else:
            pad_shape = None
        if pad_shape:
            zeros = NDArray(jnp.zeros(pad_shape, arr.dtype))
            arr = invoke("Concat", [arr, zeros], {"dim": 0})
        stacked.append(arr)
    out = stacked[0] if out_fmt else stacked
    fin = loop_vars[0] if single else loop_vars
    return out, fin


def _while_lax(cond, func, loop_vars, max_iterations):
    loop_vars, single = _as_list(loop_vars)
    # discover step-output structure with eval_shape
    out_shape = jax.eval_shape(lambda *vs: func(*vs)[0], *loop_vars)
    out_list, out_single = _as_list(out_shape)
    buffers = tuple(jnp.zeros((max_iterations,) + tuple(o.shape), o.dtype)
                    for o in out_list)

    def body_fn(carry):
        i, vars_, bufs = carry
        step_out, new_vars = func(*vars_)
        step_out, _ = _as_list(step_out)
        new_vars = list(new_vars) if isinstance(new_vars, (list, tuple)) \
            else [new_vars]
        bufs = tuple(
            lax.dynamic_update_slice(b, o[None].astype(b.dtype),
                                     (i,) + (0,) * o.ndim)
            for b, o in zip(bufs, step_out))
        return i + 1, tuple(new_vars), bufs

    def cond_fn(carry):
        i, vars_, _ = carry
        return jnp.logical_and(i < max_iterations,
                               jnp.squeeze(cond(*vars_)).astype(bool))

    i, final_vars, bufs = lax.while_loop(
        cond_fn, body_fn, (jnp.int32(0), tuple(loop_vars), buffers))
    out = bufs[0] if out_single else list(bufs)
    fin = final_vars[0] if single else list(final_vars)
    return out, fin


def cond(pred, then_func, else_func):
    """Evaluate then_func() or else_func() based on pred (reference
    contrib.cond / `_cond` control_flow.cc:1378)."""
    if isinstance(pred, NDArray):
        return then_func() if _bool_of(pred) else else_func()

    def checked(f):
        def g(_):
            out = f()
            if _is_nd(out):  # NDArray const can't cross the jit trace
                from ..base import MXNetError
                raise MXNetError(
                    "cond branch inside a hybridized forward returned an "
                    "NDArray constant; create it with F ops (or pass it "
                    "as a block parameter/input) so the branch stays "
                    "inside the compiled program")
            return out
        return g

    return lax.cond(jnp.squeeze(pred).astype(bool),
                    checked(then_func), checked(else_func), None)


def isinf(data):
    if _is_jax_val(data):  # raw jax value inside a hybridized trace
        return jnp.isinf(data).astype(data.dtype)
    return invoke("abs", [data], {}) == float("inf")


def isnan(data):
    if _is_jax_val(data):
        return jnp.isnan(data).astype(data.dtype)
    return data != data


def isfinite(data):
    if _is_jax_val(data):
        return jnp.isfinite(data).astype(data.dtype)
    fin = invoke("abs", [data], {}) != float("inf")
    notnan = (data == data)
    return fin * notnan


# ---------------------------------------------------------------------------
# DGL graph ops (reference src/operator/contrib/dgl_graph.cc). These are
# host-side graph algorithms over CSR edge structures (values = edge ids):
# sampling/subgraphing runs on numpy — irregular, data-dependent shapes
# have no sensible XLA lowering — and results wrap back into ndarrays.
# Eager-only by design (the reference likewise dispatches FComputeEx on
# CSR storage only).
# ---------------------------------------------------------------------------

def edge_id(data, u, v):
    """Edge ids data[u[i], v[i]], -1 where no edge exists
    (reference _contrib_edge_id, dgl_graph.cc:427)."""
    import numpy as np
    from .sparse import CSRNDArray
    from . import ndarray as _nd
    if not isinstance(data, CSRNDArray):
        raise TypeError("edge_id expects a CSRNDArray graph")
    indptr = np.asarray(data.indptr.asnumpy(), np.int64)
    indices = np.asarray(data.indices.asnumpy(), np.int64)
    vals = np.asarray(data.data.asnumpy())
    uu = np.asarray(u.asnumpy(), np.int64).ravel()
    vv = np.asarray(v.asnumpy(), np.int64).ravel()
    out = np.full(uu.shape, -1.0, vals.dtype)
    for i, (r, c) in enumerate(zip(uu, vv)):
        lo, hi = indptr[r], indptr[r + 1]
        hit = np.where(indices[lo:hi] == c)[0]
        if hit.size:
            out[i] = vals[lo + hit[0]]
    return _nd.array(out)


def dgl_adjacency(data):
    """CSR of edge ids -> CSR adjacency with float 1.0 values
    (reference _contrib_dgl_adjacency, dgl_graph.cc:499)."""
    import numpy as np
    from .sparse import CSRNDArray
    import jax.numpy as jnp
    if not isinstance(data, CSRNDArray):
        raise TypeError("dgl_adjacency expects a CSRNDArray graph")
    ones = jnp.ones(data.indices.shape, jnp.float32)
    return CSRNDArray(ones, data.indptr.data, data.indices.data, data.shape)


def dgl_subgraph(graph, *vids, return_mapping=False):
    """Induced subgraph(s) on vertex sets ``vids`` (reference
    _contrib_dgl_subgraph, dgl_graph.cc:247). Subgraph values renumber
    edges 0..nnz-1; with return_mapping, parallel CSRs carrying the
    PARENT edge ids are appended to the output list."""
    import numpy as np
    from .sparse import CSRNDArray
    import jax.numpy as jnp
    if not isinstance(graph, CSRNDArray):
        raise TypeError("dgl_subgraph expects a CSRNDArray graph")
    indptr = np.asarray(graph.indptr.asnumpy(), np.int64)
    indices = np.asarray(graph.indices.asnumpy(), np.int64)
    vals = np.asarray(graph.data.asnumpy())
    subs, mappings = [], []
    for vid_arr in vids:
        vset = np.asarray(vid_arr.asnumpy(), np.int64).ravel()
        pos = {int(v): i for i, v in enumerate(vset)}
        n = len(vset)
        sp_indptr = np.zeros(n + 1, np.int64)
        sp_indices, sp_eids = [], []
        for i, v in enumerate(vset):
            lo, hi = indptr[v], indptr[v + 1]
            for j in range(lo, hi):
                dst = int(indices[j])
                if dst in pos:
                    sp_indices.append(pos[dst])
                    sp_eids.append(vals[j])
            sp_indptr[i + 1] = len(sp_indices)
        sp_indices = np.asarray(sp_indices, np.int64)
        new_ids = np.arange(len(sp_indices), dtype=np.float32)
        subs.append(CSRNDArray(jnp.asarray(new_ids),
                               jnp.asarray(sp_indptr),
                               jnp.asarray(sp_indices), (n, n)))
        if return_mapping:
            mappings.append(CSRNDArray(
                jnp.asarray(np.asarray(sp_eids, np.float32)),
                jnp.asarray(sp_indptr), jnp.asarray(sp_indices), (n, n)))
    return subs + mappings


def _dgl_neighbor_sample(graph, seeds, num_hops, num_neighbor,
                         max_num_vertices, probability=None):
    import numpy as np
    from .sparse import CSRNDArray
    from . import ndarray as _nd
    import jax.numpy as jnp
    if not isinstance(graph, CSRNDArray):
        raise TypeError("neighbor sampling expects a CSRNDArray graph")
    indptr = np.asarray(graph.indptr.asnumpy(), np.int64)
    indices = np.asarray(graph.indices.asnumpy(), np.int64)
    vals = np.asarray(graph.data.asnumpy())
    # one host fetch, not one per frontier vertex per hop
    prob_np = (np.asarray(probability.asnumpy()).ravel()
               if probability is not None else None)
    seed_ids = np.asarray(seeds.asnumpy(), np.int64).ravel()
    seed_ids = seed_ids[seed_ids >= 0]
    layer_of = {int(s): 0 for s in seed_ids}
    frontier = list(seed_ids)
    for hop in range(1, num_hops + 1):
        nxt = []
        for v in frontier:
            lo, hi = indptr[v], indptr[v + 1]
            nbrs = indices[lo:hi]
            if len(nbrs) == 0:
                continue
            if prob_np is not None:
                # zero-weight neighbors are NEVER sampled (reference
                # non-uniform semantics); a vertex whose live neighbor
                # count is short just expands less
                p = prob_np[nbrs]
                nbrs = nbrs[p > 0]
                if len(nbrs) == 0:
                    continue
                p = p[p > 0]
                take = min(num_neighbor, len(nbrs))
                chosen = np.random.choice(nbrs, size=take, replace=False,
                                          p=p / p.sum())
            else:
                take = min(num_neighbor, len(nbrs))
                chosen = np.random.choice(nbrs, size=take, replace=False)
            for c in chosen:
                c = int(c)
                if c not in layer_of:
                    layer_of[c] = hop
                    nxt.append(c)
            if len(layer_of) >= max_num_vertices:
                break
        frontier = nxt
        if len(layer_of) >= max_num_vertices:
            break
    verts = sorted(layer_of)[:max_num_vertices]
    n = len(verts)
    out_verts = np.full(max_num_vertices + 1, -1, np.int64)
    out_verts[:n] = verts
    out_verts[-1] = n
    out_layer = np.full(max_num_vertices + 1, -1, np.int64)
    out_layer[:n] = [layer_of[v] for v in verts]
    # induced sub-csr among sampled vertices, parent edge ids as values
    pos = {v: i for i, v in enumerate(verts)}
    sp_indptr = np.zeros(max_num_vertices + 1, np.int64)
    sp_indices, sp_eids = [], []
    for i, v in enumerate(verts):
        lo, hi = indptr[v], indptr[v + 1]
        for j in range(lo, hi):
            dst = int(indices[j])
            if dst in pos:
                sp_indices.append(pos[dst])
                sp_eids.append(vals[j])
        sp_indptr[i + 1:] = len(sp_indices)
    sub = CSRNDArray(jnp.asarray(np.asarray(sp_eids, np.float32)),
                     jnp.asarray(sp_indptr),
                     jnp.asarray(np.asarray(sp_indices, np.int64)),
                     (max_num_vertices, max_num_vertices))
    return [_nd.array(out_verts), sub, _nd.array(out_layer)]


def dgl_csr_neighbor_uniform_sample(graph, *seeds, num_hops=1,
                                    num_neighbor=2, max_num_vertices=100,
                                    num_args=None):
    """Uniform neighborhood sampling from seed vertices (reference
    _contrib_dgl_csr_neighbor_uniform_sample). Per seed array returns
    [vertices (max+1, last slot = count, -1 pad), sampled sub-CSR with
    parent edge ids, per-vertex hop layer (-1 pad)]."""
    out = []
    for s in seeds:
        out.extend(_dgl_neighbor_sample(graph, s, num_hops, num_neighbor,
                                        max_num_vertices))
    return out


def dgl_csr_neighbor_non_uniform_sample(graph, probability, *seeds,
                                        num_hops=1, num_neighbor=2,
                                        max_num_vertices=100,
                                        num_args=None):
    """Probability-weighted variant (reference
    _contrib_dgl_csr_neighbor_non_uniform_sample)."""
    out = []
    for s in seeds:
        out.extend(_dgl_neighbor_sample(graph, s, num_hops, num_neighbor,
                                        max_num_vertices,
                                        probability=probability))
    return out


def getnnz(data, axis=None):
    """Stored-value count (reference _contrib_getnnz, contrib/nnz.cc:172):
    for CSR inputs the number of STORED values — explicit zeros included,
    per reference semantics; for dense inputs the nonzero count."""
    from .sparse import CSRNDArray
    from . import ndarray as _nd
    if isinstance(data, CSRNDArray):
        if axis is not None:
            raise NotImplementedError("getnnz(axis=...) on CSR unsupported")
        import numpy as np
        return _nd.array(np.asarray(data.indices.shape[0], np.int64))
    return _nd.invoke("_contrib_getnnz", [data], {"axis": axis})


def _make_contrib_fn(op):
    from . import register as _register
    return _register._make_op_func(op)


from ..ops.registry import contrib_surface as _contrib_surface  # noqa: E402
__getattr__, __dir__ = _contrib_surface(globals(), _make_contrib_fn)

