"""Control-flow and contrib ndarray ops (parity:
python/mxnet/ndarray/contrib.py — foreach/while_loop/cond backed by
src/operator/control_flow.cc:1255/1316/1378 subgraph ops).

TPU-native design: in eager mode these run as Python control flow over
NDArrays (the reference's imperative semantics), fully differentiable
through the tape. When the inputs are raw jax values (inside a hybridized
trace), they lower to ``lax.scan`` / ``lax.while_loop`` / ``lax.cond`` so
compiled graphs get real XLA control flow — the design SURVEY.md §7
hard-part 4 calls for.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .ndarray import NDArray, invoke

__all__ = ["foreach", "while_loop", "cond", "isinf", "isnan", "isfinite"]


def _is_nd(x):
    if isinstance(x, NDArray):
        return True
    if isinstance(x, (list, tuple)):
        return any(_is_nd(v) for v in x)
    return False


def _as_list(x):
    if isinstance(x, (list, tuple)):
        return list(x), False
    return [x], True


def foreach(body, data, init_states):
    """Run body over data slices along axis 0, threading states
    (reference contrib.foreach; symbolic analog `_foreach`
    control_flow.cc:1255)."""
    if _is_nd(data) or _is_nd(init_states):
        return _foreach_eager(body, data, init_states)
    return _foreach_lax(body, data, init_states)


def _foreach_eager(body, data, init_states):
    data_list, single_data = _as_list(data)
    states, single_state = _as_list(init_states)
    n = data_list[0].shape[0]
    outputs = []
    single_out = True
    for i in range(n):
        eles = [d[i] for d in data_list]
        x = eles[0] if single_data else eles
        st = states[0] if single_state else states
        outs, new_st = body(x, st)
        states, _ = _as_list(new_st)
        outs, single_out = _as_list(outs)
        outputs.append(outs)
    stacked = [invoke("stack", [o[j] for o in outputs], {"axis": 0})
               for j in range(len(outputs[0]))]
    out = stacked[0] if single_out else stacked
    fin = states[0] if single_state else states
    return out, fin


def _foreach_lax(body, data, init_states):
    data_list, single_data = _as_list(data)
    states, single_state = _as_list(init_states)
    single_out = {}  # filled while tracing the first step

    def step(carry, xs):
        st = carry[0] if single_state else list(carry)
        x = xs[0] if single_data else list(xs)
        outs, new_st = body(x, st)
        new_st, _ = _as_list(new_st)
        outs, so = _as_list(outs)
        single_out["v"] = so
        return tuple(new_st), tuple(outs)

    final, ys = lax.scan(step, tuple(states), tuple(data_list))
    # unwrap by the body's actual output structure (same rule as the eager
    # path), not by element count
    out = ys[0] if single_out["v"] else list(ys)
    fin = final[0] if single_state else list(final)
    return out, fin


def while_loop(cond, func, loop_vars, max_iterations=None):
    """Run func while cond(loop_vars) holds, up to max_iterations; step
    outputs are stacked and padded to max_iterations (reference
    contrib.while_loop / `_while_loop` control_flow.cc:1316)."""
    if max_iterations is None:
        raise ValueError("max_iterations is required")
    if _is_nd(loop_vars):
        return _while_eager(cond, func, loop_vars, max_iterations)
    return _while_lax(cond, func, loop_vars, max_iterations)


def _bool_of(x):
    if isinstance(x, NDArray):
        return bool(x.asscalar())
    return bool(x)


def _while_eager(cond, func, loop_vars, max_iterations):
    loop_vars, single = _as_list(loop_vars)
    steps = 0
    outputs = []
    out_fmt = None
    while steps < max_iterations and _bool_of(
            cond(*loop_vars)):
        step_out, loop_vars = func(*loop_vars)
        step_out, out_fmt_single = _as_list(step_out)
        out_fmt = out_fmt_single
        outputs.append(step_out)
        if not isinstance(loop_vars, (list, tuple)):
            loop_vars = [loop_vars]
        else:
            loop_vars = list(loop_vars)
        steps += 1
    if not outputs:
        # zero iterations: return zero-filled padded outputs, matching the
        # lax path's buffers; discover the step-output structure abstractly
        out_abs = jax.eval_shape(lambda *vs: func(*vs)[0],
                                 *[jnp.zeros(v.shape, v.dtype)
                                   for v in loop_vars])
        out_list, out_single = _as_list(out_abs)
        zeros = [NDArray(jnp.zeros((max_iterations,) + tuple(o.shape),
                                   o.dtype)) for o in out_list]
        out = zeros[0] if out_single else zeros
        fin = loop_vars[0] if single else loop_vars
        return out, fin
    # pad to max_iterations with zeros (reference semantics)
    stacked = []
    for j in range(len(outputs[0])):
        arr = invoke("stack", [o[j] for o in outputs], {"axis": 0})
        if steps < max_iterations:
            pad_shape = (max_iterations - steps,) + arr.shape[1:]
        else:
            pad_shape = None
        if pad_shape:
            zeros = NDArray(jnp.zeros(pad_shape, arr.dtype))
            arr = invoke("Concat", [arr, zeros], {"dim": 0})
        stacked.append(arr)
    out = stacked[0] if out_fmt else stacked
    fin = loop_vars[0] if single else loop_vars
    return out, fin


def _while_lax(cond, func, loop_vars, max_iterations):
    loop_vars, single = _as_list(loop_vars)
    # discover step-output structure with eval_shape
    out_shape = jax.eval_shape(lambda *vs: func(*vs)[0], *loop_vars)
    out_list, out_single = _as_list(out_shape)
    buffers = tuple(jnp.zeros((max_iterations,) + tuple(o.shape), o.dtype)
                    for o in out_list)

    def body_fn(carry):
        i, vars_, bufs = carry
        step_out, new_vars = func(*vars_)
        step_out, _ = _as_list(step_out)
        new_vars = list(new_vars) if isinstance(new_vars, (list, tuple)) \
            else [new_vars]
        bufs = tuple(
            lax.dynamic_update_slice(b, o[None].astype(b.dtype),
                                     (i,) + (0,) * o.ndim)
            for b, o in zip(bufs, step_out))
        return i + 1, tuple(new_vars), bufs

    def cond_fn(carry):
        i, vars_, _ = carry
        return jnp.logical_and(i < max_iterations,
                               jnp.squeeze(cond(*vars_)).astype(bool))

    i, final_vars, bufs = lax.while_loop(
        cond_fn, body_fn, (jnp.int32(0), tuple(loop_vars), buffers))
    out = bufs[0] if out_single else list(bufs)
    fin = final_vars[0] if single else list(final_vars)
    return out, fin


def cond(pred, then_func, else_func):
    """Evaluate then_func() or else_func() based on pred (reference
    contrib.cond / `_cond` control_flow.cc:1378)."""
    if isinstance(pred, NDArray):
        return then_func() if _bool_of(pred) else else_func()
    return lax.cond(jnp.squeeze(pred).astype(bool),
                    lambda _: then_func(), lambda _: else_func(), None)


def isinf(data):
    return invoke("abs", [data], {}) == float("inf")


def isnan(data):
    return data != data


def isfinite(data):
    import numpy as _np
    fin = invoke("abs", [data], {}) != float("inf")
    notnan = (data == data)
    return fin * notnan
