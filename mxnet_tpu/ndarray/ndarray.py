"""Eager NDArray.

Parity surface: ``python/mxnet/ndarray/ndarray.py`` (4k LoC in the
reference) backed by ``src/ndarray/ndarray.cc`` + the dependency engine.
TPU-native design:

* The payload is a ``jax.Array`` — **every eager op dispatch is already
  asynchronous** on PJRT, so the reference's ThreadedEngine var-tracking
  collapses into buffer futures; ``wait_to_read``/``asnumpy`` are the sync
  points (engine.py translates async device errors there, matching
  threaded_engine.cc:474-487 exception semantics).
* NDArray is *mutable by rebinding*: in-place ops swap ``_data`` (functional
  update under the hood — XLA donates buffers inside jit; eager rebind is a
  new buffer, same as the reference's copy-on-write-ish Chunk swap).
* Autograd: ``_ag`` carries tape linkage (AGInfo); recording wraps the op in
  ``jax.vjp`` (see mxnet_tpu/autograd.py).
"""
from __future__ import annotations

import numpy as _np

import jax
import jax.numpy as jnp

from ..base import MXNetError, normalize_dtype, numeric_types, mx_real_t
from ..context import Context, current_context, cpu
from .. import engine as _engine
from .. import autograd as _autograd
from ..ops import registry as _registry

__all__ = ["NDArray", "array", "zeros", "zeros_like", "ones", "full",
           "arange", "empty", "concat", "invoke", "waitall", "save", "load",
           "moveaxis", "imperative_invoke"]


def zeros_like(other):
    """Zeros with the shape/dtype/placement of `other` — placement includes
    mesh sharding, so optimizer state created from a replicated weight is
    itself replicated (jnp.zeros_like preserves sharding)."""
    return NDArray(jnp.zeros_like(other._data), ctx=other.context)


_X64_NARROW = {_np.dtype(_np.int64): _np.int32,
               _np.dtype(_np.uint64): _np.uint32,
               _np.dtype(_np.float64): _np.float32}


def _as_jax(x, dtype=None, ctx=None):
    dev = (ctx or current_context()).jax_device
    if not jax.config.jax_enable_x64:
        # narrow 64-bit requests deliberately (and silently) when x64 is
        # off — jax would truncate anyway but with a per-call warning
        if dtype is not None and _np.dtype(dtype) in _X64_NARROW:
            dtype = _X64_NARROW[_np.dtype(dtype)]
        elif dtype is None and isinstance(x, _np.ndarray) and \
                x.dtype in _X64_NARROW:
            dtype = _X64_NARROW[x.dtype]
    return jax.device_put(jnp.asarray(x, dtype=dtype), dev)


class NDArray:
    """Multi-dimensional, fixed-size array on a device context."""

    __slots__ = ("_data", "_ctx", "_ag", "_version", "__weakref__")

    _collect_stats = False

    def __init__(self, data, ctx=None):
        if isinstance(data, NDArray):
            data = data._data
        self._data = data
        self._ctx = ctx or _infer_ctx(data)
        self._ag = None
        self._version = 0

    # ------------------------------------------------------------------ meta
    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return self._data.dtype

    @property
    def size(self):
        return int(self._data.size)

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def context(self):
        return self._ctx

    ctx = context

    @property
    def stype(self):
        return "default"

    @property
    def data(self):
        """Raw jax array (mxnet_tpu extension; stable read snapshot)."""
        return self._data

    @property
    def grad(self):
        if self._ag is None:
            return None
        return self._ag.grad

    # ------------------------------------------------------------ conversion
    def asnumpy(self):
        from .. import profiler as _profiler
        _profiler.record_host_sync("d2h", getattr(self._data, "nbytes", 0))
        try:
            return _np.asarray(self._data)
        except Exception as e:
            raise MXNetError(str(e)) from e

    def asscalar(self):
        if self.size != 1:
            raise ValueError("The current array is not a scalar")
        return self.asnumpy().reshape(())[()]

    def item(self):
        return self.asscalar()

    def astype(self, dtype, copy=True):
        dt = normalize_dtype(dtype)
        if not copy and self.dtype == dt:
            return self
        return invoke("Cast", [self], {"dtype": dtype})

    def copy(self):
        return invoke("_copy", [self], {})

    def copyto(self, other):
        if isinstance(other, NDArray):
            # writing into a buffer preserves the buffer's placement —
            # including mesh sharding/replication, which a bare
            # ``device_put(..., ctx.jax_device)`` would collapse to one chip
            if other.shape == self.shape:
                dst = other._data.sharding
            else:
                dst = other._ctx.jax_device
            other._rebind(jax.device_put(self._data, dst))
            return other
        if isinstance(other, Context):
            return self.as_in_context(other)
        raise TypeError("copyto: expected NDArray or Context")

    def as_in_context(self, context):
        if context == self._ctx:
            return self
        out = NDArray(jax.device_put(self._data, context.jax_device), ctx=context)
        return out

    def as_in_ctx(self, context):
        return self.as_in_context(context)

    def tolist(self):
        return self.asnumpy().tolist()

    # ----------------------------------------------------------------- sync
    def wait_to_read(self):
        _engine.on_complete(self._data)

    def wait_to_write(self):
        _engine.on_complete(self._data)

    # ------------------------------------------------------------- autograd
    def attach_grad(self, grad_req="write", stype=None):
        grad_buf = zeros(self.shape, dtype=self.dtype, ctx=self._ctx)
        info = _autograd.AGInfo(node=None, grad=grad_buf, grad_req=grad_req)
        self._ag = info

    def detach(self):
        out = NDArray(self._data, ctx=self._ctx)
        return out

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        _autograd.backward([self], [out_grad] if out_grad is not None else None,
                           retain_graph=retain_graph, train_mode=train_mode)

    # ------------------------------------------------------------- mutation
    def _rebind(self, new_data):
        self._data = new_data
        self._version += 1
        _engine.sync_point([new_data])

    def __setitem__(self, key, value):
        if isinstance(value, NDArray):
            v = value._data
        elif isinstance(value, numeric_types):
            v = value
        else:
            v = jnp.asarray(_np.asarray(value), dtype=self.dtype)
        if key is None or (isinstance(key, slice) and key == slice(None)):
            if isinstance(v, (int, float)):
                self._rebind(jnp.full(self.shape, v, self.dtype))
            else:
                self._rebind(jnp.broadcast_to(
                    jnp.asarray(v, self.dtype), self.shape))
            return
        key = _norm_index(key)
        # basic slicing routes through the registered _slice_assign ops
        # (parity: src/operator/tensor/matrix_op.cc:434-459; reference
        # __setitem__ dispatches the same way, python/mxnet/ndarray/
        # ndarray.py _set_nd_basic_indexing)
        basic = key if isinstance(key, tuple) else (key,)
        if all(isinstance(k, (slice, int)) for k in basic):
            sls = tuple(k if isinstance(k, slice) else slice(k, k + 1 or None)
                        for k in basic)
            begin = [s.start for s in sls]
            end = [s.stop for s in sls]
            step = [s.step for s in sls]
            from .. import ops as _ops_pkg  # noqa: F401 (registry populated)
            if isinstance(v, (int, float)):
                new = _registry.get("_slice_assign_scalar").fn(
                    self._data, scalar=float(v), begin=begin, end=end,
                    step=step)
            else:
                # static index arithmetic: no device slice just for a shape
                tgt = tuple(len(range(*s.indices(d)))
                            for s, d in zip(sls, self.shape)) \
                    + self.shape[len(sls):]
                rhs = jnp.broadcast_to(jnp.asarray(v, self.dtype), tgt)
                new = _registry.get("_slice_assign").fn(
                    self._data, rhs, begin=begin, end=end, step=step)
            # int keys collapse axes in numpy semantics; sls kept them as
            # length-1 slices, so shapes already agree
            self._rebind(new)
            return
        self._rebind(self._data.at[key].set(v))

    def __getitem__(self, key):
        if isinstance(key, NDArray):
            key = key._data.astype(jnp.int32)
        key = _norm_index(key)
        out = self._data[key]
        nd = NDArray(out, ctx=self._ctx)
        if _autograd.is_recording() and self._ag is not None:
            _, vjp = jax.vjp(lambda d: d[key], self._data)
            _autograd.record_op(lambda ct: vjp(ct), [self], [nd], name="getitem")
        return nd

    def __len__(self):
        return self.shape[0]

    def __iter__(self):
        for i in range(self.shape[0]):
            yield self[i]

    # ------------------------------------------------------------ operators
    def __add__(self, other):
        return _binary("broadcast_add", "_plus_scalar", self, other)

    def __radd__(self, other):
        return self.__add__(other)

    def __iadd__(self, other):
        out = self.__add__(other)
        self._rebind(out._data)
        return self

    def __sub__(self, other):
        return _binary("broadcast_sub", "_minus_scalar", self, other)

    def __rsub__(self, other):
        return _binary_r("broadcast_sub", "_rminus_scalar", self, other)

    def __isub__(self, other):
        out = self.__sub__(other)
        self._rebind(out._data)
        return self

    def __mul__(self, other):
        return _binary("broadcast_mul", "_mul_scalar", self, other)

    def __rmul__(self, other):
        return self.__mul__(other)

    def __imul__(self, other):
        out = self.__mul__(other)
        self._rebind(out._data)
        return self

    def __truediv__(self, other):
        return _binary("broadcast_div", "_div_scalar", self, other)

    def __rtruediv__(self, other):
        return _binary_r("broadcast_div", "_rdiv_scalar", self, other)

    def __itruediv__(self, other):
        out = self.__truediv__(other)
        self._rebind(out._data)
        return self

    def __mod__(self, other):
        return _binary("broadcast_mod", "_mod_scalar", self, other)

    def __rmod__(self, other):
        return _binary_r("broadcast_mod", "_rmod_scalar", self, other)

    def __pow__(self, other):
        return _binary("broadcast_power", "_power_scalar", self, other)

    def __rpow__(self, other):
        return _binary_r("broadcast_power", "_rpower_scalar", self, other)

    def __neg__(self):
        return invoke("negative", [self], {})

    def __abs__(self):
        return invoke("abs", [self], {})

    def __eq__(self, other):
        if other is None:
            return False
        return _binary("broadcast_equal", "_equal_scalar", self, other)

    def __ne__(self, other):
        if other is None:
            return True
        return _binary("broadcast_not_equal", "_not_equal_scalar", self, other)

    def __gt__(self, other):
        return _binary("broadcast_greater", "_greater_scalar", self, other)

    def __ge__(self, other):
        return _binary("broadcast_greater_equal", "_greater_equal_scalar", self, other)

    def __lt__(self, other):
        return _binary("broadcast_lesser", "_lesser_scalar", self, other)

    def __le__(self, other):
        return _binary("broadcast_lesser_equal", "_lesser_equal_scalar", self, other)

    def __hash__(self):
        return id(self)

    def __bool__(self):
        if self.size == 1:
            return bool(self.asscalar())
        raise ValueError("ambiguous truth value of multi-element NDArray")

    def __repr__(self):
        return "%s\n<NDArray %s @%s>" % (
            str(self.asnumpy()), "x".join(str(s) for s in self.shape), self._ctx)

    # ------------------------------------------------ fluent method wrappers
    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (list, tuple)):
            shape = tuple(shape[0])
        return invoke("Reshape", [self], {"shape": shape, **kwargs})

    def reshape_like(self, other, **kwargs):
        return invoke("reshape_like", [self, other], kwargs)

    def flatten(self):
        return invoke("Flatten", [self], {})

    def transpose(self, axes=None):
        return invoke("transpose", [self], {"axes": axes})

    @property
    def T(self):
        return self.transpose()

    def expand_dims(self, axis):
        return invoke("expand_dims", [self], {"axis": axis})

    def squeeze(self, axis=None):
        return invoke("squeeze", [self], {"axis": axis})

    def broadcast_to(self, shape):
        return invoke("broadcast_to", [self], {"shape": shape})

    def broadcast_like(self, other):
        return invoke("broadcast_like", [self, other], {})

    def slice(self, begin, end, step=None):
        return invoke("slice", [self], {"begin": begin, "end": end, "step": step})

    def slice_axis(self, axis, begin, end):
        return invoke("slice_axis", [self], {"axis": axis, "begin": begin, "end": end})

    def take(self, indices, axis=0, mode="clip"):
        return invoke("take", [self, indices], {"axis": axis, "mode": mode})

    def one_hot(self, depth, **kw):
        return invoke("one_hot", [self], {"depth": depth, **kw})

    def sum(self, axis=None, keepdims=False):
        return invoke("sum", [self], {"axis": axis, "keepdims": keepdims})

    def mean(self, axis=None, keepdims=False):
        return invoke("mean", [self], {"axis": axis, "keepdims": keepdims})

    def max(self, axis=None, keepdims=False):
        return invoke("max", [self], {"axis": axis, "keepdims": keepdims})

    def min(self, axis=None, keepdims=False):
        return invoke("min", [self], {"axis": axis, "keepdims": keepdims})

    def prod(self, axis=None, keepdims=False):
        return invoke("prod", [self], {"axis": axis, "keepdims": keepdims})

    def norm(self, ord=2, axis=None, keepdims=False):
        return invoke("norm", [self], {"ord": ord, "axis": axis, "keepdims": keepdims})

    def argmax(self, axis=None, keepdims=False):
        return invoke("argmax", [self], {"axis": axis, "keepdims": keepdims})

    def argmin(self, axis=None, keepdims=False):
        return invoke("argmin", [self], {"axis": axis, "keepdims": keepdims})

    def argsort(self, axis=-1, is_ascend=True):
        return invoke("argsort", [self], {"axis": axis, "is_ascend": is_ascend})

    def sort(self, axis=-1, is_ascend=True):
        return invoke("sort", [self], {"axis": axis, "is_ascend": is_ascend})

    def topk(self, **kw):
        return invoke("topk", [self], kw)

    def clip(self, a_min, a_max):
        return invoke("clip", [self], {"a_min": a_min, "a_max": a_max})

    def abs(self):
        return invoke("abs", [self], {})

    def sign(self):
        return invoke("sign", [self], {})

    def sqrt(self):
        return invoke("sqrt", [self], {})

    def square(self):
        return invoke("square", [self], {})

    def exp(self):
        return invoke("exp", [self], {})

    def log(self):
        return invoke("log", [self], {})

    def relu(self):
        return invoke("relu", [self], {})

    def sigmoid(self):
        return invoke("sigmoid", [self], {})

    def tanh(self):
        return invoke("tanh", [self], {})

    def softmax(self, axis=-1):
        return invoke("softmax", [self], {"axis": axis})

    def log_softmax(self, axis=-1):
        return invoke("log_softmax", [self], {"axis": axis})

    def dot(self, other, **kw):
        return invoke("dot", [self, other], kw)

    def pick(self, index, axis=-1, keepdims=False):
        return invoke("pick", [self, index], {"axis": axis, "keepdims": keepdims})

    def flip(self, axis):
        return invoke("flip", [self], {"axis": axis})

    def tile(self, reps):
        return invoke("tile", [self], {"reps": reps})

    def repeat(self, repeats, axis=None):
        return invoke("repeat", [self], {"repeats": repeats, "axis": axis})

    def pad(self, **kw):
        return invoke("pad", [self], kw)

    def split(self, num_outputs, axis=1, squeeze_axis=False):
        return invoke("split", [self], {"num_outputs": num_outputs,
                                        "axis": axis,
                                        "squeeze_axis": squeeze_axis})

    def tostype(self, stype):
        if stype == "default":
            return self
        from . import sparse as _sp
        return _sp.cast_storage(self, stype)


def _binary(op_name, scalar_op, lhs, rhs):
    if isinstance(rhs, NDArray):
        return invoke(op_name, [lhs, rhs], {})
    return invoke(scalar_op, [lhs], {"scalar": float(rhs)})


def _binary_r(op_name, rscalar_op, lhs, rhs):
    """rhs OP lhs where rhs is scalar or NDArray (reflected operators)."""
    if isinstance(rhs, NDArray):
        return invoke(op_name, [rhs, lhs], {})
    return invoke(rscalar_op, [lhs], {"scalar": float(rhs)})


def _infer_ctx(data):
    try:
        dev = list(data.devices())[0]
        if dev.platform == "cpu":
            return Context("cpu", dev.id)
        return Context("tpu", dev.id)
    except Exception:
        return current_context()


def _norm_index(key):
    if isinstance(key, NDArray):
        return key._data.astype(jnp.int32)
    if isinstance(key, tuple):
        return tuple(_norm_index(k) for k in key)
    return key


# ---------------------------------------------------------------------------
# op invocation (analog of MXImperativeInvokeEx → Imperative::Invoke,
# reference src/c_api/c_api_ndarray.cc:81-143 / src/imperative/imperative.cc:87)
# ---------------------------------------------------------------------------

def invoke(op_name, inputs, params, out=None):
    from .. import profiler as _profiler
    _prof = _profiler._active and _profiler._state.profile_imperative
    if _prof:
        _prof_t0 = _profiler._now_us()
    op = _registry.get(op_name)
    params = {k: v for k, v in params.items() if v is not None or k in ("axis",)}
    # explicit device placement for no-input ops (creation/random): reference
    # semantics place the output on the requested ctx
    req_ctx = params.pop("ctx", None)
    if req_ctx is not None and not isinstance(req_ctx, Context):
        req_ctx = None
    arrs = [x._data if isinstance(x, NDArray) else jnp.asarray(x) for x in inputs]
    if "_training" in op.param_names and "_training" not in params:
        params["_training"] = _autograd.is_training()

    recording = (_autograd.is_recording()
                 and any(isinstance(x, NDArray) and x._ag is not None
                         for x in inputs))
    # only floating-point inputs are differentiable; ints/bools are constants
    diff_idx = [i for i, a in enumerate(arrs)
                if jnp.issubdtype(a.dtype, jnp.floating)]
    if recording and not diff_idx:
        recording = False
    if recording:
        diff_arrs = [arrs[i] for i in diff_idx]

        def fn(*xs):
            full = list(arrs)
            for i, x in zip(diff_idx, xs):
                full[i] = x
            if op.is_random:
                from .. import random as _random
                with _random.trace_scope(_base_key):
                    return op.fn(*full, **params)
            return op.fn(*full, **params)

        if op.is_random:
            from .. import random as _random
            _base_key = _random.next_key()
        out_data, vjp_fn = jax.vjp(fn, *diff_arrs)
    else:
        if req_ctx is not None:
            with jax.default_device(req_ctx.jax_device):
                out_data = op.fn(*arrs, **params)
        else:
            out_data = op.fn(*arrs, **params)
        vjp_fn = None

    single = not isinstance(out_data, tuple)
    outs_data = (out_data,) if single else out_data
    if req_ctx is not None:
        ctx = req_ctx
    elif inputs and isinstance(inputs[0], NDArray):
        ctx = inputs[0]._ctx
    else:
        ctx = current_context()

    # commit hidden aux-update outputs in place (reference eager BatchNorm
    # mutates moving_mean/moving_var aux inputs) and trim to visible outputs
    if op.aux_outputs:
        training = params.get("_training", True)
        if training:
            for in_slot, out_slot in zip(op.aux_inputs, op.aux_outputs):
                if in_slot < len(inputs) and isinstance(inputs[in_slot], NDArray):
                    inputs[in_slot]._rebind(outs_data[out_slot])
        n_vis = op.resolve_num_visible_outputs(params)
        if vjp_fn is not None and n_vis < len(outs_data):
            # tape sees only visible outputs; pad hidden cotangents with zeros
            hidden = [(o.shape, o.dtype) for o in outs_data[n_vis:]]
            orig_vjp = vjp_fn

            def vjp_fn(cot, _orig=orig_vjp, _hidden=hidden):
                cots = cot if isinstance(cot, tuple) else (cot,)
                padded = tuple(cots) + tuple(jnp.zeros(s, d) for s, d in _hidden)
                return _orig(padded)
        outs_data = outs_data[:n_vis]
        single = n_vis == 1

    out_nds = [NDArray(d, ctx=ctx) for d in outs_data]
    _engine.sync_point([d for d in outs_data])
    if _prof:
        # profiling measures to completion (the reference's engine events
        # cover kernel execution, not just dispatch)
        for d in outs_data:
            if hasattr(d, "block_until_ready"):
                try:
                    d.block_until_ready()
                except Exception:
                    pass
        _profiler.record_event(op_name, "operator", _prof_t0,
                               _profiler._now_us() - _prof_t0)

    if recording:
        _autograd.record_op(vjp_fn, [inputs[i] for i in diff_idx], out_nds,
                            name=op_name)

    if out is not None:
        targets = out if isinstance(out, (list, tuple)) else [out]
        for t, o in zip(targets, out_nds):
            t._rebind(o._data)
            if o._ag is not None:
                # carry tape linkage so autograd flows through out=; when not
                # recording (e.g. optimizer updates), keep the target's own
                # AGInfo so leaf grad sinks survive in-place updates
                t._ag = o._ag
        return out
    return out_nds[0] if single else tuple(out_nds)


def imperative_invoke(op_name, *inputs, out=None, **params):
    return invoke(op_name, list(inputs), params, out=out)


# ---------------------------------------------------------------------------
# creation
# ---------------------------------------------------------------------------

def array(source_array, ctx=None, dtype=None):
    if isinstance(source_array, NDArray):
        src = source_array.asnumpy()
        if dtype is None:
            dtype = src.dtype
    elif isinstance(source_array, _np.ndarray):
        src = source_array
        if dtype is None:
            dtype = src.dtype
    else:
        # python lists/scalars default to float32 (reference
        # python/mxnet/ndarray/ndarray.py `array`: float32 unless source
        # carries an explicit dtype)
        src = _np.asarray(source_array)
        if dtype is None:
            dtype = mx_real_t
    return NDArray(_as_jax(src, normalize_dtype(dtype), ctx), ctx=ctx or current_context())


def empty(shape, ctx=None, dtype=None):
    return zeros(shape, ctx=ctx, dtype=dtype)


def zeros(shape, ctx=None, dtype=None, **kwargs):
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    dt = normalize_dtype(dtype) or _np.float32
    return NDArray(_as_jax(jnp.zeros(shape, dt), None, ctx), ctx=ctx or current_context())


def ones(shape, ctx=None, dtype=None, **kwargs):
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    dt = normalize_dtype(dtype) or _np.float32
    return NDArray(_as_jax(jnp.ones(shape, dt), None, ctx), ctx=ctx or current_context())


def full(shape, val, ctx=None, dtype=None):
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    dt = normalize_dtype(dtype) or _np.float32
    return NDArray(_as_jax(jnp.full(shape, val, dt), None, ctx), ctx=ctx or current_context())


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype=None):
    return invoke("_arange", [], {"start": start, "stop": stop, "step": step,
                                  "repeat": repeat, "dtype": dtype or "float32"})


def moveaxis(tensor, source, destination):
    return NDArray(jnp.moveaxis(tensor._data, source, destination), ctx=tensor._ctx)


def concat(*data, dim=1):
    return invoke("Concat", list(data), {"dim": dim})


def waitall():
    _engine.waitall()


def asnumpy_all(*arrays):
    """Fetch several arrays to host in ONE blocking device->host sync.

    The batched counterpart of per-array ``asnumpy()``: N separate
    fetches in a loop body are N device round-trips (mxlint MXL103);
    this moves the whole tuple in a single ``jax.device_get``. Non-device
    values (numpy, scalars) pass through unchanged.

        loss_h, out_h, label_h = nd.asnumpy_all(loss, out, label)
    """
    devs = [a._data if isinstance(a, NDArray) else a for a in arrays]
    pending = [d for d in devs if hasattr(d, "block_until_ready")]
    if pending:
        from .. import profiler as _profiler
        _profiler.record_host_sync(
            "d2h", sum(int(getattr(d, "nbytes", 0)) for d in pending))
        import jax
        devs = jax.device_get(devs)
    return tuple(_np.asarray(d) for d in devs)


# ---------------------------------------------------------------------------
# serialization — reference binary .params format (ndarray.cc:1583-1795),
# see serialization.py for the wire layout. Round-1/2 npz files still load.
# ---------------------------------------------------------------------------

_MAGIC = b"MXTPU001"  # legacy (rounds 1-2) npz container magic, read-only


def _to_record(a):
    """NDArray -> serialization record (numpy or sparse tuple)."""
    stype = getattr(a, "stype", "default")
    if stype == "row_sparse":
        return ("row_sparse", _np.asarray(a.data.asnumpy()),
                _np.asarray(a.indices.asnumpy()), a.shape)
    if stype == "csr":
        return ("csr", _np.asarray(a.data.asnumpy()),
                _np.asarray(a.indptr.asnumpy()),
                _np.asarray(a.indices.asnumpy()), a.shape)
    return a.asnumpy()


def _from_record(rec):
    if isinstance(rec, _np.ndarray):
        return array(rec)
    from .sparse import RowSparseNDArray, CSRNDArray
    if rec[0] == "row_sparse":
        _, data, indices, shape = rec
        return RowSparseNDArray(jnp.asarray(data), jnp.asarray(indices),
                                shape)
    _, data, indptr, indices, shape = rec
    return CSRNDArray(jnp.asarray(data), jnp.asarray(indptr),
                      jnp.asarray(indices), shape)


def save(fname, data):
    """Serialize NDArrays (list or name->array dict) to a file in the
    reference's versioned binary .params format (list magic 0x112,
    per-array V2 records — src/ndarray/ndarray.cc:1583-1795), so
    checkpoints interoperate with reference-lineage MXNet in both
    directions. Dense, row_sparse and csr arrays round-trip."""
    from . import serialization as _ser
    if isinstance(data, NDArray):
        data = [data]
    if isinstance(data, dict):
        keys = list(data.keys())
        arrays = [data[k] for k in keys]
    else:
        keys = []
        arrays = list(data)
    _ser.save_file(fname, [_to_record(a) for a in arrays], keys)


def load(fname):
    """Load a .params file: the reference binary format (including V1/V0
    legacy per-array records), or the npz container earlier builds of
    this library wrote."""
    from . import serialization as _ser
    with open(fname, "rb") as f:
        head = f.read(8)
    if head == _MAGIC:
        return _load_npz_legacy(fname)
    arrays, names = _ser.load_file(fname)
    arrays = [_from_record(r) for r in arrays]
    if not names:
        return arrays
    return dict(zip(names, arrays))


def _load_npz_legacy(fname):
    with open(fname, "rb") as f:
        f.read(8)
        z = _np.load(f, allow_pickle=False)
        keys = list(z["__keys__"])
        if not keys:
            out = []
            i = 0
            while "arr_%d" % i in z:
                out.append(array(z["arr_%d" % i]))
                i += 1
            return out
        return {str(k): array(z["data_" + str(k)]) for k in keys}
