"""``mx.nd.op`` namespace (reference ndarray/op.py — the module the
code generator populates with every public operator). Resolves any
non-underscore registry op lazily."""
from ..ops.registry import namespaced_surface as _ns, list_ops as _list
from .register import _make_op_func as _mk

__getattr__, __dir__ = _ns(
    globals(), _mk,
    resolve=lambda n: None if n.startswith("_") else n,
    listing=lambda: [n for n in _list() if not n.startswith("_")])
