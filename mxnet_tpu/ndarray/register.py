"""Generate module-level eager op functions from the registry.

Parity: the reference generates one Python function per registered op at
import time by introspecting the C op registry
(python/mxnet/ndarray/register.py:20-43). Here codegen is a thin closure per
op: split NDArray inputs from keyword hyperparams, route through
ndarray.invoke (the Imperative::Invoke analog).
"""
from __future__ import annotations

import sys

from ..ops import registry as _registry
from . import ndarray as _nd


def _make_op_func(op):
    def fn(*args, out=None, name=None, **kwargs):
        args, kwargs = op.bind_positional(args, kwargs)
        inputs = []
        for a in args:
            if isinstance(a, _nd.NDArray):
                inputs.append(a)
            elif a is None:
                inputs.append(None)
            else:
                # allow raw numerics/ndarrays as inputs
                inputs.append(_nd.array(a))
        # drop trailing None inputs (optional args like bias with no_bias)
        while inputs and inputs[-1] is None:
            inputs.pop()
        inputs = [x for x in inputs if x is not None]
        return _nd.invoke(op.name, inputs, kwargs, out=out)
    fn.__name__ = op.name
    fn.__doc__ = op.doc
    return fn


def populate(module_name):
    mod = sys.modules[module_name]
    for name in _registry.list_ops():
        op = _registry.get(name)
        setattr(mod, name, _make_op_func(op))
