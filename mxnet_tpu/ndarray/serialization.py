"""Reference-compatible binary NDArray serialization.

Byte-for-byte implementation of the reference's versioned .params format
(src/ndarray/ndarray.cc:1583-1795):

file container  : uint64 magic 0x112, uint64 reserved, then the dmlc
                  vector encodings — uint64 count + per-array payloads,
                  uint64 count + (uint64 len + bytes) per name.
per-array (V2)  : uint32 0xF993fac9; int32 storage type; [sparse only:
                  storage shape]; shape; int32 dev_type + int32 dev_id;
                  int32 mshadow type flag; [sparse only: per-aux int32
                  type flag + shape]; raw data bytes; [aux data bytes].
shapes          : uint32 ndim + int64 * ndim (nnvm::TShape wire form).
legacy (V1/V0)  : magic 0xF993fac8 (shape follows) or a raw uint32 ndim
                  with uint32 dims — both read, never written.

Checkpoints written here load in reference-lineage MXNet and vice versa.
All arrays land on (and are written from) the host; the caller places
them on devices.
"""
import struct

import numpy as _np

from ..base import MXNetError

NDARRAY_V1_MAGIC = 0xF993FAC8
NDARRAY_V2_MAGIC = 0xF993FAC9
LIST_MAGIC = 0x112

# mshadow type flags (mshadow/base.h)
_TYPE_FLAGS = [
    (_np.dtype(_np.float32), 0),
    (_np.dtype(_np.float64), 1),
    (_np.dtype(_np.float16), 2),
    (_np.dtype(_np.uint8), 3),
    (_np.dtype(_np.int32), 4),
    (_np.dtype(_np.int8), 5),
    (_np.dtype(_np.int64), 6),
]
_DTYPE_TO_FLAG = {d: f for d, f in _TYPE_FLAGS}
_FLAG_TO_DTYPE = {f: d for d, f in _TYPE_FLAGS}

# NDArrayStorageType (include/mxnet/ndarray.h)
_STYPE_DEFAULT = 1
_STYPE_ROW_SPARSE = 2
_STYPE_CSR = 3
_STYPE_NAMES = {_STYPE_DEFAULT: "default", _STYPE_ROW_SPARSE: "row_sparse",
                _STYPE_CSR: "csr"}
_NUM_AUX = {_STYPE_DEFAULT: 0, _STYPE_ROW_SPARSE: 1, _STYPE_CSR: 2}


def _write_shape(out, shape):
    out += struct.pack("<I", len(shape))
    out += struct.pack("<%dq" % len(shape), *shape)


def _read(f, n):
    data = f.read(n)
    if len(data) != n:
        raise MXNetError("truncated NDArray file")
    return data


def _read_shape(f):
    (ndim,) = struct.unpack("<I", _read(f, 4))
    return struct.unpack("<%dq" % ndim, _read(f, 8 * ndim)) if ndim else ()


def _to_flag(dtype):
    dtype = _np.dtype(dtype)
    if dtype not in _DTYPE_TO_FLAG:
        raise MXNetError("dtype %s has no mshadow type flag (the reference "
                         "format cannot represent it)" % dtype)
    return _DTYPE_TO_FLAG[dtype]


def save_array(out, arr):
    """Append one array's V2 record to bytearray ``out``.

    ``arr``: numpy array (dense), or tuple ("row_sparse", data, indices,
    shape) / ("csr", data, indptr, indices, shape).
    """
    out += struct.pack("<I", NDARRAY_V2_MAGIC)
    if isinstance(arr, _np.ndarray):
        if arr.ndim == 0:
            # reference-lineage MXNet has no 0-d arrays; an ndim-0 shape on
            # the wire means "none" and carries no payload, so scalars are
            # projected to shape (1,)
            arr = arr.reshape(1)
        out += struct.pack("<i", _STYPE_DEFAULT)
        _write_shape(out, arr.shape)
        out += struct.pack("<ii", 1, 0)  # Context: kCPU=1, dev_id 0
        out += struct.pack("<i", _to_flag(arr.dtype))
        out += _np.ascontiguousarray(arr).tobytes()
        return

    kind = arr[0]
    if kind == "row_sparse":
        _, data, indices, shape = arr
        out += struct.pack("<i", _STYPE_ROW_SPARSE)
        _write_shape(out, data.shape)        # storage shape
        _write_shape(out, shape)             # logical shape
        out += struct.pack("<ii", 1, 0)
        out += struct.pack("<i", _to_flag(data.dtype))
        out += struct.pack("<i", _to_flag(indices.dtype))
        _write_shape(out, indices.shape)
        out += _np.ascontiguousarray(data).tobytes()
        out += _np.ascontiguousarray(indices).tobytes()
    elif kind == "csr":
        _, data, indptr, indices, shape = arr
        out += struct.pack("<i", _STYPE_CSR)
        _write_shape(out, data.shape)
        _write_shape(out, shape)
        out += struct.pack("<ii", 1, 0)
        out += struct.pack("<i", _to_flag(data.dtype))
        # aux order: indptr then indices (ndarray.h kIndPtr=0, kIdx=1)
        out += struct.pack("<i", _to_flag(indptr.dtype))
        _write_shape(out, indptr.shape)
        out += struct.pack("<i", _to_flag(indices.dtype))
        _write_shape(out, indices.shape)
        out += _np.ascontiguousarray(data).tobytes()
        out += _np.ascontiguousarray(indptr).tobytes()
        out += _np.ascontiguousarray(indices).tobytes()
    else:
        raise MXNetError("unknown array record kind %r" % (kind,))


def _read_dense_payload(f, shape):
    (_dev_type, _dev_id) = struct.unpack("<ii", _read(f, 8))
    (flag,) = struct.unpack("<i", _read(f, 4))
    dtype = _FLAG_TO_DTYPE[flag]
    n = int(_np.prod(shape)) if shape else 1
    data = _np.frombuffer(_read(f, dtype.itemsize * n), dtype=dtype)
    return data.reshape(shape).copy()


def load_array(f):
    """Read one array record. Returns numpy (dense) or the tuple forms of
    :func:`save_array` (sparse)."""
    (magic,) = struct.unpack("<I", _read(f, 4))
    if magic != NDARRAY_V2_MAGIC:
        # V1: magic then TShape; V0: the magic IS ndim, dims are uint32
        if magic == NDARRAY_V1_MAGIC:
            shape = _read_shape(f)
        else:
            ndim = magic
            if ndim > 8:  # not a plausible legacy record
                raise MXNetError("invalid NDArray record magic 0x%x" % magic)
            shape = struct.unpack("<%dI" % ndim, _read(f, 4 * ndim))
        if not shape:
            return _np.zeros((), _np.float32)
        return _read_dense_payload(f, shape)

    (stype,) = struct.unpack("<i", _read(f, 4))
    if stype not in _NUM_AUX:
        raise MXNetError("unknown storage type %d" % stype)
    nad = _NUM_AUX[stype]
    sshape = _read_shape(f) if nad else None
    shape = _read_shape(f)
    if not shape:
        return _np.zeros((), _np.float32)
    if nad == 0:
        return _read_dense_payload(f, shape)

    (_dev_type, _dev_id) = struct.unpack("<ii", _read(f, 8))
    (flag,) = struct.unpack("<i", _read(f, 4))
    dtype = _FLAG_TO_DTYPE[flag]
    aux = []
    for _ in range(nad):
        (aflag,) = struct.unpack("<i", _read(f, 4))
        ashape = _read_shape(f)
        aux.append((_FLAG_TO_DTYPE[aflag], ashape))
    n = int(_np.prod(sshape)) if sshape else 0
    data = _np.frombuffer(_read(f, dtype.itemsize * n),
                          dtype=dtype).reshape(sshape).copy()
    aux_data = []
    for adtype, ashape in aux:
        an = int(_np.prod(ashape)) if ashape else 0
        aux_data.append(_np.frombuffer(
            _read(f, adtype.itemsize * an), dtype=adtype)
            .reshape(ashape).copy())
    if stype == _STYPE_ROW_SPARSE:
        return ("row_sparse", data, aux_data[0], shape)
    return ("csr", data, aux_data[0], aux_data[1], shape)


def save_file(fname, arrays, names):
    """Write the list container (reference NDArray::Save, ndarray.cc:1785)."""
    out = bytearray()
    out += struct.pack("<QQ", LIST_MAGIC, 0)
    out += struct.pack("<Q", len(arrays))
    for a in arrays:
        save_array(out, a)
    out += struct.pack("<Q", len(names))
    for name in names:
        raw = name.encode("utf-8")
        out += struct.pack("<Q", len(raw))
        out += raw
    with open(fname, "wb") as f:
        f.write(out)


def load_file(fname):
    """Read the list container -> (arrays, names)."""
    with open(fname, "rb") as f:
        magic, _reserved = struct.unpack("<QQ", _read(f, 16))
        if magic != LIST_MAGIC:
            raise MXNetError("%s is not an NDArray list file "
                             "(magic 0x%x)" % (fname, magic))
        (count,) = struct.unpack("<Q", _read(f, 8))
        arrays = [load_array(f) for _ in range(count)]
        (n_names,) = struct.unpack("<Q", _read(f, 8))
        names = []
        for _ in range(n_names):
            (ln,) = struct.unpack("<Q", _read(f, 8))
            names.append(_read(f, ln).decode("utf-8"))
    return arrays, names
