"""``mx.nd.linalg`` namespace (parity: python/mxnet/ndarray/linalg.py).

Re-exports the registry-generated eager wrappers (out= support, raw-numpy
coercion) under their reference names; the op list lives once, in
ops/linalg.py."""
from ..ops.linalg import LINALG_NAMES
from . import register as _register
from ..ops import registry as _registry

for _name in LINALG_NAMES:
    globals()[_name] = _register._make_op_func(
        _registry.get("_linalg_" + _name))
del _name
