"""``mx.nd._internal`` namespace (reference ndarray/_internal.py — the
underscore-prefixed generated operators, e.g. ``_plus_scalar``)."""
from ..ops.registry import namespaced_surface as _ns, list_ops as _list
from .register import _make_op_func as _mk

__getattr__, __dir__ = _ns(
    globals(), _mk,
    resolve=lambda n: n if n.startswith("_") else None,
    listing=lambda: [n for n in _list() if n.startswith("_")])
