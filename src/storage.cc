// Pooled host storage manager.
//
// Parity: the reference's per-device caching allocators
// (src/storage/pooled_storage_manager.h:51 GPUPooledStorageManager,
// cpu_shared_storage_manager.h). Device (HBM) memory on TPU is owned by
// PJRT/XLA, so the native allocator's remaining job is HOST memory: staging
// buffers for infeed, decoded-image batches, checkpoint serialization.
// Strategy mirrors the reference's pow2-rounding pool
// (MXNET_GPU_MEM_POOL_TYPE=Round): freed blocks are kept in size-class free
// lists and reused, eliminating malloc/free churn in the data pipeline.
//
// C ABI consumed via ctypes (mxnet_tpu/runtime.py).

#include <cstdint>
#include <cstdlib>
#include <map>
#include <mutex>
#include <unordered_map>
#include <vector>

namespace mxtpu {

class StoragePool {
 public:
  explicit StoragePool(size_t reserve_limit = 0)
      : limit_(reserve_limit), pooled_bytes_(0), used_bytes_(0) {}

  ~StoragePool() {
    ReleaseAll();
    // Free blocks still outstanding (allocated, never Free'd): the pool
    // owns every allocation it handed out, so teardown must reclaim them
    // or they leak.
    std::lock_guard<std::mutex> lk(mu_);
    for (auto& kv : sizes_) std::free(kv.first);
    sizes_.clear();
    used_bytes_ = 0;
  }

  void* Alloc(size_t size) {
    size_t cls = RoundSize(size);
    {
      std::lock_guard<std::mutex> lk(mu_);
      auto it = free_.find(cls);
      if (it != free_.end() && !it->second.empty()) {
        void* p = it->second.back();
        it->second.pop_back();
        pooled_bytes_ -= cls;
        used_bytes_ += cls;
        sizes_[p] = cls;
        return p;
      }
    }
    // 64-byte alignment: XLA's CPU client CHECK-fails handing it a host
    // buffer below its minimum alignment, and TPU infeed DMA wants
    // cacheline-aligned staging anyway (RoundSize keeps cls a multiple
    // of the alignment)
    void* p = std::aligned_alloc(64, cls < 64 ? 64 : cls);
    if (p == nullptr) return nullptr;
    std::lock_guard<std::mutex> lk(mu_);
    sizes_[p] = cls;
    used_bytes_ += cls;
    return p;
  }

  void Free(void* p) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = sizes_.find(p);
    if (it == sizes_.end()) return;  // not ours / double free: no-op
    size_t cls = it->second;
    used_bytes_ -= cls;
    // drop the live-block entry so a double Free is detected above;
    // Alloc re-registers the size when the pooled block is reused
    sizes_.erase(it);
    if (limit_ == 0 || pooled_bytes_ + cls <= limit_) {
      free_[cls].push_back(p);
      pooled_bytes_ += cls;
    } else {
      std::free(p);
    }
  }

  void DirectFree(void* p) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = sizes_.find(p);
    if (it == sizes_.end()) return;  // unknown or already freed: no-op
    used_bytes_ -= it->second;
    sizes_.erase(it);
    std::free(p);
  }

  void ReleaseAll() {
    std::lock_guard<std::mutex> lk(mu_);
    for (auto& kv : free_) {
      for (void* p : kv.second) {
        sizes_.erase(p);
        std::free(p);
      }
      kv.second.clear();
    }
    pooled_bytes_ = 0;
  }

  size_t PooledBytes() {
    std::lock_guard<std::mutex> lk(mu_);
    return pooled_bytes_;
  }

  size_t UsedBytes() {
    std::lock_guard<std::mutex> lk(mu_);
    return used_bytes_;
  }

 private:
  static size_t RoundSize(size_t size) {
    // round up to the next power of two >= 64 (reference pow2 pool)
    size_t cls = 64;
    while (cls < size) cls <<= 1;
    return cls;
  }

  std::mutex mu_;
  std::map<size_t, std::vector<void*>> free_;
  std::unordered_map<void*, size_t> sizes_;
  size_t limit_;
  size_t pooled_bytes_;
  size_t used_bytes_;
};

}  // namespace mxtpu

extern "C" {

void* StorageCreate(uint64_t reserve_limit) {
  return new mxtpu::StoragePool(reserve_limit);
}

void StorageDestroy(void* h) { delete static_cast<mxtpu::StoragePool*>(h); }

void* StorageAlloc(void* h, uint64_t size) {
  return static_cast<mxtpu::StoragePool*>(h)->Alloc(size);
}

void StorageFree(void* h, void* p) {
  static_cast<mxtpu::StoragePool*>(h)->Free(p);
}

void StorageDirectFree(void* h, void* p) {
  static_cast<mxtpu::StoragePool*>(h)->DirectFree(p);
}

void StorageReleaseAll(void* h) {
  static_cast<mxtpu::StoragePool*>(h)->ReleaseAll();
}

uint64_t StoragePooledBytes(void* h) {
  return static_cast<mxtpu::StoragePool*>(h)->PooledBytes();
}

uint64_t StorageUsedBytes(void* h) {
  return static_cast<mxtpu::StoragePool*>(h)->UsedBytes();
}

}  // extern "C"
