// Fast RecordIO scanner.
//
// Parity: the reference's dmlc-core recordio reader used by the data
// pipeline (src/io/iter_image_recordio_2.cc parser threads). Byte format is
// identical to mxnet_tpu/recordio.py (magic 0xced7230a, cflag:3|len:29,
// 4-byte alignment); this C++ path memory-maps/slurps the file once and
// indexes every record so the python DataLoader can fetch records with zero
// per-record syscalls or byte-copies (ctypes returns pointers into the
// buffer).

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

namespace mxtpu {

static const uint32_t kMagic = 0xced7230a;

struct RecordIndex {
  uint64_t offset;  // payload offset in buffer
  uint64_t length;  // payload length (possibly re-assembled)
};

class RecordReader {
 public:
  ~RecordReader() {
    if (map_ != nullptr && map_ != MAP_FAILED) munmap(map_, map_size_);
  }

  bool Load(const char* path) {
    // mmap instead of slurping: ImageNet-scale .rec files are tens of GB;
    // the page cache keeps hot records resident without owning the RSS
    int fd = ::open(path, O_RDONLY);
    if (fd < 0) return false;
    struct stat st;
    if (fstat(fd, &st) != 0) {
      ::close(fd);
      return false;
    }
    map_size_ = static_cast<size_t>(st.st_size);
    if (map_size_ > 0) {
      map_ = mmap(nullptr, map_size_, PROT_READ, MAP_PRIVATE, fd, 0);
      if (map_ == MAP_FAILED) {
        ::close(fd);
        map_ = nullptr;
        return false;
      }
    }
    ::close(fd);
    return Index();
  }

  int64_t NumRecords() const { return static_cast<int64_t>(index_.size()); }

  const char* Record(int64_t i, int64_t* len) const {
    if (i < 0 || i >= NumRecords()) {
      *len = 0;
      return nullptr;
    }
    const RecordIndex& r = index_[i];
    *len = static_cast<int64_t>(r.length);
    if (r.length == 0) return Base();
    // multi-part records were re-assembled into assembled_
    if (r.offset & kAssembledBit) {
      return assembled_[r.offset & ~kAssembledBit].data();
    }
    return Base() + r.offset;
  }

 private:
  static const uint64_t kAssembledBit = 1ull << 63;

  const char* Base() const { return static_cast<const char*>(map_); }

  bool Index() {
    size_t pos = 0;
    const size_t n = map_size_;
    while (pos + 8 <= n) {
      uint32_t magic, lrec;
      std::memcpy(&magic, Base() + pos, 4);
      std::memcpy(&lrec, Base() + pos + 4, 4);
      if (magic != kMagic) return false;
      uint32_t cflag = lrec >> 29;
      uint64_t length = lrec & ((1u << 29) - 1);
      size_t payload = pos + 8;
      if (payload + length > n) return false;
      size_t next = payload + ((length + 3u) & ~3ull);
      if (cflag == 0) {
        index_.push_back({payload, length});
      } else {
        // multi-part record: assemble continuation chunks
        std::string out(Base() + payload, length);
        pos = next;
        while (pos + 8 <= n) {
          std::memcpy(&magic, Base() + pos, 4);
          std::memcpy(&lrec, Base() + pos + 4, 4);
          if (magic != kMagic) return false;
          uint32_t cf = lrec >> 29;
          uint64_t l2 = lrec & ((1u << 29) - 1);
          size_t pl = pos + 8;
          if (pl + l2 > n) return false;
          out.append(Base() + pl, l2);
          pos = pl + ((l2 + 3u) & ~3ull);
          if (cf == 3) break;
        }
        index_.push_back(
            {kAssembledBit | assembled_.size(), out.size()});
        assembled_.push_back(std::move(out));
        continue;
      }
      pos = next;
    }
    return true;
  }

  void* map_ = nullptr;
  size_t map_size_ = 0;
  std::vector<RecordIndex> index_;
  std::vector<std::string> assembled_;
};

}  // namespace mxtpu

extern "C" {

void* RecordReaderCreate(const char* path) {
  auto* r = new mxtpu::RecordReader();
  if (!r->Load(path)) {
    delete r;
    return nullptr;
  }
  return r;
}

void RecordReaderDestroy(void* h) {
  delete static_cast<mxtpu::RecordReader*>(h);
}

int64_t RecordReaderNum(void* h) {
  return static_cast<mxtpu::RecordReader*>(h)->NumRecords();
}

const char* RecordReaderGet(void* h, int64_t i, int64_t* len) {
  return static_cast<mxtpu::RecordReader*>(h)->Record(i, len);
}

}  // extern "C"
