// Host-side dependency engine.
//
// TPU-native role: PJRT already schedules device work asynchronously, so the
// device half of the reference's ThreadedEngine (src/engine/threaded_engine.h
// :269, threaded_engine_perdevice.cc) collapses into buffer futures. What the
// host still needs — and what this engine provides — is the reference's
// var-serialized async scheduling for HOST work: IO prefetch, custom python
// ops (src/operator/custom/custom-inl.h:50 runs these on a dedicated worker),
// checkpoint writes. Semantics match include/mxnet/engine.h: NewVariable,
// PushAsync(fn, const_vars, mutable_vars), WaitForVar, WaitForAll; reads on a
// var run concurrently, writes serialize against all earlier ops, and ops
// never run before their dependencies — the invariant the reference's
// tests/cpp/engine/threaded_engine_test.cc stresses.
//
// Exposed as a flat C ABI (the reference's L4 discipline) consumed from
// python via ctypes (mxnet_tpu/runtime.py).

#include <algorithm>
#include <condition_variable>
#include <cstdint>
#include <deque>
#include <mutex>
#include <queue>
#include <thread>
#include <unordered_map>
#include <unordered_set>
#include <vector>

extern "C" {
typedef void (*EngineFn)(void* arg);
}

namespace mxtpu {

struct Opr;

struct VarRecord {
  Opr* opr;
  bool write;
};

struct Var {
  std::deque<VarRecord> queue;  // ops waiting for this var, FIFO
  int active_readers = 0;
  bool active_writer = false;
  bool alive = true;
};

struct Opr {
  EngineFn fn;
  void* arg;
  std::vector<int64_t> const_vars;
  std::vector<int64_t> mut_vars;
  int wait = 0;  // vars that have not yet granted this op
};

class Engine {
 public:
  explicit Engine(int num_workers) : shutdown_(false), pending_(0) {
    if (num_workers < 1) num_workers = 1;
    for (int i = 0; i < num_workers; ++i) {
      workers_.emplace_back([this]() { WorkerLoop(); });
    }
  }

  ~Engine() {
    WaitForAll();
    {
      std::unique_lock<std::mutex> lk(mu_);
      shutdown_ = true;
    }
    ready_cv_.notify_all();
    for (auto& t : workers_) t.join();
  }

  int64_t NewVariable() {
    std::unique_lock<std::mutex> lk(mu_);
    int64_t id = next_var_++;
    vars_.emplace(id, Var());
    return id;
  }

  void DeleteVariable(int64_t id) {
    // deletion is itself a write op: runs after all users finish
    // (reference Engine::DeleteVariable, include/mxnet/engine.h:220)
    int64_t vid = id;
    Engine* self = this;
    struct DelCtx { Engine* e; int64_t v; };
    auto* ctx = new DelCtx{self, vid};
    PushAsync(
        [](void* a) {
          auto* c = static_cast<DelCtx*>(a);
          std::unique_lock<std::mutex> lk(c->e->mu_);
          auto it = c->e->vars_.find(c->v);
          if (it != c->e->vars_.end()) it->second.alive = false;
          delete c;
        },
        ctx, nullptr, 0, &vid, 1);
  }

  void PushAsync(EngineFn fn, void* arg, const int64_t* cvars, int n_const,
                 const int64_t* mvars, int n_mut) {
    Opr* opr = new Opr();
    opr->fn = fn;
    opr->arg = arg;
    // Dedupe var ids: a duplicate entry (listed twice in mutable, or in
    // both const and mutable) would enqueue the op twice on one var queue;
    // the second entry can never be granted and the engine deadlocks. The
    // reference engine rejects duplicates — we dedupe, with mutable
    // winning over const.
    std::unordered_set<int64_t> seen;
    for (int i = 0; i < n_mut; ++i) {
      if (seen.insert(mvars[i]).second) opr->mut_vars.push_back(mvars[i]);
    }
    for (int i = 0; i < n_const; ++i) {
      if (seen.insert(cvars[i]).second) opr->const_vars.push_back(cvars[i]);
    }
    {
      std::unique_lock<std::mutex> lk(mu_);
      ++pending_;
      opr->wait = static_cast<int>(opr->const_vars.size() +
                                   opr->mut_vars.size());
      if (opr->wait == 0) {
        ready_.push(opr);
        ready_cv_.notify_one();
      } else {
        for (int64_t v : opr->const_vars)
          vars_[v].queue.push_back({opr, false});
        for (int64_t v : opr->mut_vars)
          vars_[v].queue.push_back({opr, true});
        for (int64_t v : opr->const_vars) TryGrant(v);
        for (int64_t v : opr->mut_vars) TryGrant(v);
      }
    }
  }

  void WaitForVar(int64_t id) {
    std::unique_lock<std::mutex> lk(mu_);
    done_cv_.wait(lk, [this, id]() {
      auto it = vars_.find(id);
      if (it == vars_.end()) return true;
      const Var& v = it->second;
      return v.queue.empty() && v.active_readers == 0 && !v.active_writer;
    });
  }

  void WaitForAll() {
    std::unique_lock<std::mutex> lk(mu_);
    done_cv_.wait(lk, [this]() { return pending_ == 0; });
  }

  int PendingCount() {
    std::unique_lock<std::mutex> lk(mu_);
    return pending_;
  }

 private:
  // Grant queued ops on var v while the head of the queue can run:
  // consecutive reads run together; a write runs exclusively. Called with
  // mu_ held.
  void TryGrant(int64_t vid) {
    Var& v = vars_[vid];
    while (!v.queue.empty()) {
      VarRecord& head = v.queue.front();
      if (head.write) {
        if (v.active_readers > 0 || v.active_writer) break;
        v.active_writer = true;
        Opr* o = head.opr;
        v.queue.pop_front();
        Granted(o);
      } else {
        if (v.active_writer) break;
        ++v.active_readers;
        Opr* o = head.opr;
        v.queue.pop_front();
        Granted(o);
      }
    }
  }

  // Erase a deleted variable once nothing references it anymore (called
  // with mu_ held) — prevents the unbounded vars_ growth of a
  // var-per-iteration usage pattern.
  void MaybeErase(int64_t vid) {
    auto it = vars_.find(vid);
    if (it == vars_.end()) return;
    const Var& v = it->second;
    if (!v.alive && v.queue.empty() && v.active_readers == 0 &&
        !v.active_writer) {
      vars_.erase(it);
    }
  }

  void Granted(Opr* o) {
    if (--o->wait == 0) {
      ready_.push(o);
      ready_cv_.notify_one();
    }
  }

  void WorkerLoop() {
    for (;;) {
      Opr* opr = nullptr;
      {
        std::unique_lock<std::mutex> lk(mu_);
        ready_cv_.wait(lk, [this]() { return shutdown_ || !ready_.empty(); });
        if (shutdown_ && ready_.empty()) return;
        opr = ready_.front();
        ready_.pop();
      }
      opr->fn(opr->arg);
      {
        std::unique_lock<std::mutex> lk(mu_);
        for (int64_t vid : opr->const_vars) {
          auto it = vars_.find(vid);
          if (it == vars_.end()) continue;
          --it->second.active_readers;
          TryGrant(vid);
          MaybeErase(vid);
        }
        for (int64_t vid : opr->mut_vars) {
          auto it = vars_.find(vid);
          if (it == vars_.end()) continue;
          it->second.active_writer = false;
          TryGrant(vid);
          MaybeErase(vid);
        }
        --pending_;
      }
      delete opr;
      done_cv_.notify_all();
    }
  }

  friend struct DelHelper;

 public:
  std::mutex mu_;
  std::unordered_map<int64_t, Var> vars_;

 private:
  std::queue<Opr*> ready_;
  std::condition_variable ready_cv_;
  std::condition_variable done_cv_;
  std::vector<std::thread> workers_;
  bool shutdown_;
  int pending_;
  int64_t next_var_ = 1;
};

}  // namespace mxtpu

extern "C" {

void* EngineCreate(int num_workers) {
  return new mxtpu::Engine(num_workers);
}

void EngineDestroy(void* h) { delete static_cast<mxtpu::Engine*>(h); }

int64_t EngineNewVariable(void* h) {
  return static_cast<mxtpu::Engine*>(h)->NewVariable();
}

void EngineDeleteVariable(void* h, int64_t v) {
  static_cast<mxtpu::Engine*>(h)->DeleteVariable(v);
}

void EnginePushAsync(void* h, EngineFn fn, void* arg, const int64_t* cvars,
                     int n_const, const int64_t* mvars, int n_mut) {
  static_cast<mxtpu::Engine*>(h)->PushAsync(fn, arg, cvars, n_const, mvars,
                                            n_mut);
}

void EngineWaitForVar(void* h, int64_t v) {
  static_cast<mxtpu::Engine*>(h)->WaitForVar(v);
}

void EngineWaitForAll(void* h) {
  static_cast<mxtpu::Engine*>(h)->WaitForAll();
}

int EnginePendingCount(void* h) {
  return static_cast<mxtpu::Engine*>(h)->PendingCount();
}

}  // extern "C"
