#!/usr/bin/env python
"""Train a small GPT-style causal transformer LM with flash attention.

The long-context demo: gluon blocks assembled around the pallas flash
attention op (`mx.nd.contrib.FlashAttention`, causal, f32 accumulation —
ops/pallas_flash.py). The training task is a lag-k COPY task (the target
at position t is the input token from position t-k), which a causal
transformer can only solve by attending k steps back — so a falling loss
demonstrates real long-range attention, not local statistics.

Scaling notes (docs/parallelism.md): the same attention call runs
sharded over a sequence axis via `mxnet_tpu.parallel.ring_attention`
(ppermute ring, verified against dense in the multichip dryrun), and the
Dense layers take Megatron shardings via `megatron_tp_rule`.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _common import maybe_force_cpu, pick_ctx, check_improved  # noqa: E402
maybe_force_cpu()

import logging
logging.basicConfig(level=logging.INFO)

import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import gluon, autograd
from mxnet_tpu.gluon import nn


class CausalSelfAttention(gluon.HybridBlock):
    def __init__(self, dim, num_heads, **kw):
        super().__init__(**kw)
        assert dim % num_heads == 0
        self._h = num_heads
        self._d = dim // num_heads
        with self.name_scope():
            self.qkv = nn.Dense(3 * dim, use_bias=True, flatten=False)
            self.proj = nn.Dense(dim, use_bias=True, flatten=False)

    def hybrid_forward(self, F, x):
        # x: (N, T, C)
        qkv = self.qkv(x)                                  # (N, T, 3C)
        q, k, v = F.split(qkv, num_outputs=3, axis=-1)

        def heads(t):   # (N, T, C) -> (N, H, T, D)
            t = F.reshape(t, shape=(0, 0, -4, self._h, self._d))
            return F.transpose(t, axes=(0, 2, 1, 3))
        out = F.contrib.FlashAttention(heads(q), heads(k), heads(v),
                                       causal=True)
        out = F.transpose(out, axes=(0, 2, 1, 3))          # (N, T, H, D)
        out = F.reshape(out, shape=(0, 0, -3))             # merge H*D
        return self.proj(out)


class Block(gluon.HybridBlock):
    def __init__(self, dim, num_heads, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.ln1 = nn.LayerNorm()
            self.attn = CausalSelfAttention(dim, num_heads)
            self.ln2 = nn.LayerNorm()
            self.mlp1 = nn.Dense(4 * dim, activation="relu", flatten=False)
            self.mlp2 = nn.Dense(dim, flatten=False)

    def hybrid_forward(self, F, x):
        x = x + self.attn(self.ln1(x))
        return x + self.mlp2(self.mlp1(self.ln2(x)))


class GPT(gluon.HybridBlock):
    def __init__(self, vocab, dim, num_heads, num_layers, seq_len, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.tok = nn.Embedding(vocab, dim)
            self.pos = self.params.get("pos_weight", shape=(seq_len, dim),
                                       init=mx.initializer.Normal(0.02))
            self.blocks = nn.HybridSequential()
            for _ in range(num_layers):
                self.blocks.add(Block(dim, num_heads))
            self.ln_f = nn.LayerNorm()
            self.head = nn.Dense(vocab, flatten=False)

    def hybrid_forward(self, F, x, pos):
        h = self.tok(x) + F.expand_dims(pos, axis=0)
        h = self.blocks(h)
        return self.head(self.ln_f(h))


def make_copy_batch(rng, batch, seq_len, vocab, lag):
    x = rng.randint(1, vocab, (batch, seq_len))
    y = np.zeros_like(x)
    y[:, lag:] = x[:, :-lag]        # predict the token lag steps back
    return x.astype("f4"), y.astype("f4")


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--vocab", type=int, default=64)
    p.add_argument("--dim", type=int, default=64)
    p.add_argument("--num-heads", type=int, default=4)
    p.add_argument("--num-layers", type=int, default=2)
    p.add_argument("--seq-len", type=int, default=64)
    p.add_argument("--lag", type=int, default=17,
                   help="copy distance: attention must reach this far back")
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--steps", type=int, default=120)
    p.add_argument("--lr", type=float, default=3e-3)
    p.add_argument("--device", default=None)
    args = p.parse_args()
    assert args.lag < args.seq_len

    dev = pick_ctx()
    net = GPT(args.vocab, args.dim, args.num_heads, args.num_layers,
              args.seq_len)
    net.initialize(mx.initializer.Xavier(), ctx=dev)
    net.hybridize(static_alloc=True)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})

    rng = np.random.RandomState(0)
    losses = []
    for step in range(args.steps):
        xb, yb = make_copy_batch(rng, args.batch_size, args.seq_len,
                                 args.vocab, args.lag)
        x = mx.nd.array(xb, ctx=dev)
        y = mx.nd.array(yb, ctx=dev)
        with autograd.record():
            logits = net(x)                       # (N, T, V)
            # score only positions with a defined target (t >= lag)
            loss = loss_fn(logits[:, args.lag:, :], y[:, args.lag:]).mean()
        loss.backward()
        trainer.step(1)
        losses.append(float(loss.asnumpy()))
        if step % 40 == 0:
            logging.info("step %d loss %.4f", step, losses[-1])

    chance = float(np.log(args.vocab))
    print("loss first->last: %.3f -> %.3f (chance %.3f)"
          % (losses[0], losses[-1], chance))
    check_improved("lm loss", [losses[0], min(losses[-10:])])
    assert min(losses[-10:]) < 0.6 * chance, \
        "attention did not learn the lag-%d copy" % args.lag


if __name__ == "__main__":
    main()
