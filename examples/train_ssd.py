#!/usr/bin/env python
"""End-to-end SSD detector training (reference example/ssd/train.py
workflow): ImageDetIter over a detection .rec -> multibox anchors/targets
-> SoftmaxOutput(cls) + smooth_l1/MakeLoss(loc) -> Module.fit (fused
one-program step under kvstore=tpu_sync).

With --data-rec absent, a synthetic detection .rec is generated (colored
rectangles on noise, the box IS the object) so the script runs anywhere
and the loss measurably decreases.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _common import maybe_force_cpu  # noqa: E402
maybe_force_cpu()

import logging
logging.basicConfig(level=logging.INFO)

import numpy as np
import mxnet_tpu as mx


def ssd_symbol(num_classes=3, num_anchors_per_pos=4):
    """Tiny SSD: conv backbone, two detection scales, multibox head
    (reference example/ssd/symbol/symbol_builder.py get_symbol_train)."""
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("label")

    def conv_block(x, nf, name, stride=1):
        x = mx.sym.Convolution(x, kernel=(3, 3), stride=(stride, stride),
                               pad=(1, 1), num_filter=nf, name=name)
        x = mx.sym.BatchNorm(x, name=name + "_bn")
        return mx.sym.Activation(x, act_type="relu")

    x = conv_block(data, 16, "c1")
    x = mx.sym.Pooling(x, kernel=(2, 2), stride=(2, 2), pool_type="max")
    x = conv_block(x, 32, "c2")
    feat1 = conv_block(x, 32, "c3")                      # stride 2 scale
    feat2 = conv_block(feat1, 64, "c4", stride=2)        # stride 4 scale

    loc_preds, cls_preds, anchors = [], [], []
    for i, (feat, size) in enumerate([(feat1, 0.3), (feat2, 0.6)]):
        na = num_anchors_per_pos
        loc = mx.sym.Convolution(feat, kernel=(3, 3), pad=(1, 1),
                                 num_filter=na * 4, name="loc%d" % i)
        loc = mx.sym.Flatten(mx.sym.transpose(loc, axes=(0, 2, 3, 1)))
        loc_preds.append(loc)
        cls = mx.sym.Convolution(feat, kernel=(3, 3), pad=(1, 1),
                                 num_filter=na * (num_classes + 1),
                                 name="cls%d" % i)
        cls = mx.sym.Flatten(mx.sym.transpose(cls, axes=(0, 2, 3, 1)))
        cls_preds.append(cls)
        anchors.append(mx.sym.contrib.MultiBoxPrior(
            feat, sizes=(size, size * 1.3), ratios=(1.0, 2.0, 0.5),
            name="anchors%d" % i))
    loc_preds = mx.sym.Concat(*loc_preds, dim=1, name="multibox_loc_pred")
    cls_preds = mx.sym.Concat(*cls_preds, dim=1)
    cls_preds = mx.sym.reshape(cls_preds, shape=(0, -1, num_classes + 1))
    cls_preds = mx.sym.transpose(cls_preds, axes=(0, 2, 1),
                                 name="multibox_cls_pred")
    anchors = mx.sym.Concat(*anchors, dim=1, name="multibox_anchors")

    tmp = mx.sym.contrib.MultiBoxTarget(
        anchors, label, cls_preds, overlap_threshold=0.5, ignore_label=-1,
        negative_mining_ratio=3, minimum_negative_samples=0,
        negative_mining_thresh=0.5, variances=(0.1, 0.1, 0.2, 0.2),
        name="multibox_target")
    loc_target, loc_target_mask, cls_target = tmp[0], tmp[1], tmp[2]

    cls_prob = mx.sym.SoftmaxOutput(cls_preds, cls_target, ignore_label=-1,
                                    use_ignore=True, multi_output=True,
                                    normalization="valid", name="cls_prob")
    loc_loss_ = mx.sym.smooth_l1(loc_target_mask * (loc_preds - loc_target),
                                 scalar=1.0, name="loc_loss_")
    loc_loss = mx.sym.MakeLoss(loc_loss_, normalization="valid",
                               name="loc_loss")
    cls_label = mx.sym.MakeLoss(cls_target, grad_scale=0, name="cls_label")
    return mx.sym.Group([cls_prob, loc_loss, cls_label])


def make_synthetic_rec(path_prefix, n=64, side=64, num_classes=3, seed=0):
    """Detection .rec: each image carries 1-2 solid class-colored boxes."""
    import cv2
    from mxnet_tpu import recordio
    rng = np.random.RandomState(seed)
    colors = [(255, 64, 64), (64, 255, 64), (64, 64, 255)]
    rec, idx = path_prefix + ".rec", path_prefix + ".idx"
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(n):
        im = rng.randint(0, 60, (side, side, 3), np.uint8)
        boxes = []
        for _ in range(rng.randint(1, 3)):
            cls = rng.randint(0, num_classes)
            x1, y1 = rng.uniform(0.05, 0.5, 2)
            bw, bh = rng.uniform(0.25, 0.45, 2)
            x2, y2 = min(x1 + bw, 0.95), min(y1 + bh, 0.95)
            cv2.rectangle(im, (int(x1 * side), int(y1 * side)),
                          (int(x2 * side), int(y2 * side)),
                          colors[cls], -1)
            boxes.append([cls, x1, y1, x2, y2])
        header = [2, 5]
        for b in boxes:
            header.extend(b)
        ok, buf = cv2.imencode(".jpg", im)
        w.write_idx(i, recordio.pack(
            recordio.IRHeader(len(header), np.array(header, np.float32),
                              i, 0), buf.tobytes()))
    w.close()
    return rec


class MultiBoxMetric(mx.metric.EvalMetric):
    """Cross-entropy + smooth-l1 composite (reference example/ssd
    MultiBoxMetric): reads the network's own outputs."""

    def __init__(self):
        super().__init__("multibox")

    def update(self, labels, preds):
        cls_prob = preds[0].asnumpy()      # (B, C+1, A)
        loc_loss = preds[1].asnumpy()
        cls_target = preds[2].asnumpy()    # (B, A)
        valid = cls_target >= 0
        idx = cls_target.astype(int)
        probs = np.take_along_axis(
            cls_prob, idx[:, None, :].clip(0), axis=1)[:, 0, :]
        ce = -np.log(np.maximum(probs[valid], 1e-9)).sum()
        self.sum_metric += ce + loc_loss.sum()
        self.num_inst += max(int(valid.sum()), 1)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--data-rec", default=None,
                   help=".rec with detection labels (default: synthetic)")
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--data-shape", type=int, default=64)
    p.add_argument("--num-classes", type=int, default=3)
    p.add_argument("--epochs", type=int, default=4)
    p.add_argument("--lr", type=float, default=0.01)
    p.add_argument("--kv-store", default="tpu_sync")
    p.add_argument("--prefix", default="/tmp/mxtpu_ssd",
                   help="checkpoint prefix")
    p.add_argument("--device", default=None)
    args = p.parse_args()

    rec = args.data_rec
    if rec is None:
        rec = make_synthetic_rec("/tmp/mxtpu_ssd_synth",
                                 num_classes=args.num_classes,
                                 side=args.data_shape)
        print("synthetic detection data at %s" % rec)

    from mxnet_tpu import image as img
    it = img.ImageDetIter(batch_size=args.batch_size,
                          data_shape=(3, args.data_shape, args.data_shape),
                          path_imgrec=rec, shuffle=True, rand_mirror=True,
                          mean=True, std=True)
    it = mx.io.ResizeIter(it, size=max(1, 64 // args.batch_size))

    sym = ssd_symbol(args.num_classes)
    mod = mx.mod.Module(sym, data_names=("data",), label_names=("label",))
    metric = MultiBoxMetric()
    losses = []

    def epoch_cb(epoch, symbol, arg_p, aux_p):
        losses.append(metric.get()[1])

    mod.fit(it, num_epoch=args.epochs, optimizer="sgd",
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9,
                              "wd": 5e-4},
            initializer=mx.initializer.Xavier(),
            kvstore=args.kv_store, eval_metric=metric,
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 4),
            epoch_end_callback=epoch_cb)
    mod.save_checkpoint(args.prefix, args.epochs)
    print("loss per epoch: %s" % ["%.3f" % v for v in losses])
    if losses[-1] >= losses[0]:
        raise SystemExit("loss did not decrease: %s" % losses)
    print("SSD training OK: loss %.3f -> %.3f; checkpoint at %s"
          % (losses[0], losses[-1], args.prefix))


if __name__ == "__main__":
    main()
