#!/usr/bin/env python
"""Long-context attention across a device mesh: the sequence-parallel
demo (ring attention over an `sp` axis, `mxnet_tpu.parallel`).

What it shows, end to end:

1. `make_ring_attention(mesh)` shards (B, H, T, D) tensors on T across
   the mesh and rotates KV shards around the ring with `ppermute` — each
   device only ever holds T/n_devices keys/values, so max sequence
   length scales LINEARLY with devices (the whole point of ring/context
   parallelism).
2. The sharded result matches single-device dense attention on a size
   where dense still fits.
3. A sequence too big for the per-device budget to hold full KV runs
   fine sharded.

Run on real chips the same way — the mesh comes from jax.devices(); here
`--devices 8` uses the virtual CPU mesh
(XLA_FLAGS=--xla_force_host_platform_device_count=8, set automatically
when no accelerator is present).
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--devices", type=int, default=8)
    p.add_argument("--seq-len", type=int, default=4096)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--head-dim", type=int, default=32)
    p.add_argument("--causal", action=argparse.BooleanOptionalAction,
                   default=True)
    p.add_argument("--device", default=None,
                   help="cpu forces the virtual mesh; default: cpu mesh "
                        "unless --device tpu is given")
    p.add_argument("--skip-dense-check", action="store_true",
                   help="skip the O(T^2) dense cross-check (REQUIRED for "
                        "sequences whose full score matrix cannot fit — "
                        "the sharded path itself has no such limit)")
    args = p.parse_args()

    # virtual multi-device CPU mesh unless the user explicitly asked for
    # the accelerator; must be set BEFORE jax initializes
    if args.device != "tpu":
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=%d" % args.devices)
        from _common import maybe_force_cpu
        maybe_force_cpu(["--device", "cpu"])

    import numpy as np
    import jax
    import mxnet_tpu  # noqa: F401  (platform pinning, registry)
    from mxnet_tpu.parallel import make_mesh
    from mxnet_tpu.parallel.ring_attention import (
        make_ring_attention, attention_reference)

    devs = jax.devices()
    n = min(args.devices, len(devs))
    mesh = make_mesh({"sp": n})
    print("mesh: %d x %s" % (n, devs[0].platform))

    rng = np.random.RandomState(0)
    B, H, T, D = 1, args.heads, args.seq_len, args.head_dim
    assert T % n == 0, "--seq-len must be divisible by the mesh size %d" % n
    q = rng.randn(B, H, T, D).astype("f4") * 0.3
    k = rng.randn(B, H, T, D).astype("f4") * 0.3
    v = rng.randn(B, H, T, D).astype("f4")

    ring = make_ring_attention(mesh, causal=args.causal)
    out = ring(q, k, v)
    out_np = np.asarray(jax.device_get(out))

    # 1) per-device sharding really happened
    shard_t = {s.data.shape[2] for s in out.addressable_shards}
    print("per-device T shards:", sorted(shard_t), "of full T =", T)
    assert shard_t == {T // n}

    # 2) numerics match dense attention (skippable: the dense check is
    # the ONLY O(T^2)-memory step here — the sharded path streams KV)
    if args.skip_dense_check:
        print("dense cross-check skipped (sequence beyond dense memory)")
    else:
        want = np.asarray(attention_reference(q, k, v, causal=args.causal))
        np.testing.assert_allclose(out_np, want, rtol=2e-4, atol=2e-4)
        print("ring(%d devices) == dense: max |diff| %.2e"
              % (n, float(np.abs(out_np - want).max())))

    # 3) KV memory per device is T/n of the full sequence
    kv_full_mb = 2 * q.nbytes / 1e6
    print("KV held per device: %.1f MB vs %.1f MB unsharded (%dx less)"
          % (kv_full_mb / n, kv_full_mb, n))
    print("LONG-CONTEXT OK")


if __name__ == "__main__":
    main()
