#!/usr/bin/env python
"""Sparse linear classification (reference example/sparse/
linear_classification/train.py workflow): LibSVMIter streams CSR
batches, the weight's gradient is row-sparse, and the optimizer updates
only the touched rows lazily — the ps-lite workflow re-homed onto the
kvstore surface (dist_sync/dist_async both work under tools/launch.py).

--data takes a libsvm file (the reference uses criteo/avazu); without it
a synthetic sparse classification problem is generated.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _common import maybe_force_cpu, check_improved  # noqa: E402
maybe_force_cpu()

import logging
logging.basicConfig(level=logging.INFO)

import numpy as np
import mxnet_tpu as mx


def make_synthetic_libsvm(path, n=2000, dim=1000, nnz=12, seed=0):
    """Linearly separable sparse data: y = sign(w_true . x)."""
    nnz = min(nnz, dim)
    rng = np.random.RandomState(seed)
    w_true = rng.randn(dim)
    with open(path, "w") as f:
        for _ in range(n):
            idx = rng.choice(dim, nnz, replace=False)
            val = rng.randn(nnz)
            y = 1 if np.dot(w_true[idx], val) > 0 else 0
            f.write("%d %s\n" % (y, " ".join(
                "%d:%.4f" % (i, v) for i, v in sorted(zip(idx, val)))))
    return path


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--data", default=None, help="libsvm training file")
    p.add_argument("--num-features", type=int, default=1000)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--epochs", type=int, default=6)
    p.add_argument("--lr", type=float, default=0.5)
    p.add_argument("--kv-store", default="local",
                   help="local | dist_sync | dist_async (under launch.py)")
    p.add_argument("--optimizer", default="adagrad")
    p.add_argument("--device", default=None)
    args = p.parse_args()

    data_path = args.data or make_synthetic_libsvm(
        "/tmp/mxtpu_sparse_lc.libsvm", dim=args.num_features)
    kv = mx.kv.create(args.kv_store)
    it = mx.io.LibSVMIter(data_libsvm=data_path,
                          data_shape=(args.num_features,),
                          batch_size=args.batch_size,
                          num_parts=kv.num_workers, part_index=kv.rank)

    # row-sparse weight synchronized THROUGH the kvstore (the reference
    # workflow): the optimizer runs kvstore-side, push aggregates each
    # worker's sparse gradient (dist_sync) or applies it immediately
    # (dist_async), and pull fetches the fresh weights
    weight = mx.nd.zeros((args.num_features, 1))
    bias = mx.nd.zeros((1,))
    kv.set_optimizer(mx.optimizer.create(args.optimizer,
                                         learning_rate=args.lr))
    kv.init(0, weight)
    kv.init(1, bias)

    from mxnet_tpu.ndarray import sparse as sp
    accs = []
    for epoch in range(args.epochs):
        it.reset()
        correct = total = 0
        for batch in it:
            x = batch.data[0]                       # CSRNDArray (B, D)
            # one device->host sync for label, logits, and bias
            # (mxlint MXL103)
            y, logits_h, bias_h = mx.nd.asnumpy_all(
                batch.label[0], sp.dot(x, weight), bias)
            logits = logits_h.ravel() + float(bias_h.ravel()[0])
            prob = 1.0 / (1.0 + np.exp(-logits))
            # logistic grad wrt logits
            g = (prob - y)[:, None].astype("f4") / len(y)
            # dL/dW = X^T g — row-sparse: only features present in the
            # batch get nonzero rows
            gw_dense = sp.dot(x, mx.nd.array(g), transpose_a=True)
            gw = sp.cast_storage(gw_dense, "row_sparse")
            kv.push(0, gw)
            kv.push(1, mx.nd.array([float(g.sum())]))
            kv.pull(0, out=weight, ignore_sparse=False)
            kv.pull(1, out=bias)
            correct += int(((prob > 0.5) == (y > 0.5)).sum())
            total += len(y)
        accs.append(correct / total)
        logging.info("epoch %d: accuracy %.3f", epoch, accs[-1])
    check_improved("accuracy", accs, lower_is_better=False)
    print("sparse linear classification OK: acc %.3f -> %.3f "
          "(%d workers, %s)" % (accs[0], accs[-1], kv.num_workers,
                                args.kv_store))


if __name__ == "__main__":
    main()
