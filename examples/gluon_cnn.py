#!/usr/bin/env python
"""Gluon imperative -> hybridized CNN training (reference
example/gluon/mnist.py workflow), on synthetic image data so it runs
anywhere. Shows autograd.record + Trainer, then hybridize for the
compiled fast path."""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _common import maybe_force_cpu  # noqa: E402
maybe_force_cpu()

import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import gluon, autograd


def net_fn():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(16, kernel_size=3, padding=1, activation="relu"),
            gluon.nn.MaxPool2D(2),
            gluon.nn.Conv2D(32, kernel_size=3, padding=1, activation="relu"),
            gluon.nn.GlobalAvgPool2D(),
            gluon.nn.Flatten(),
            gluon.nn.Dense(10))
    return net


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--device", default="auto",
                    choices=["auto", "cpu"])
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--num-epochs", type=int, default=3)
    ap.add_argument("--no-hybridize", action="store_true")
    args = ap.parse_args()

    rng = np.random.RandomState(0)
    n = 1024
    y = rng.randint(0, 10, n)
    X = rng.rand(n, 1, 16, 16).astype(np.float32) * 0.1
    for i in range(n):  # class-dependent mean intensity (GAP-friendly)
        X[i] += (int(y[i]) + 1) * 0.25
    ds = gluon.data.ArrayDataset(X, y.astype(np.float32))
    loader = gluon.data.DataLoader(ds, batch_size=args.batch_size,
                                   shuffle=True)

    net = net_fn()
    net.initialize(mx.initializer.Xavier())
    if not args.no_hybridize:
        net.hybridize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 3e-3})

    for epoch in range(args.num_epochs):
        total = correct = 0
        cum_loss = 0.0
        for data, label in loader:
            with autograd.record():
                out = net(data)
                loss = loss_fn(out, label)
            loss.backward()
            trainer.step(args.batch_size)
            # one device->host sync for all three (mxlint MXL103)
            loss_h, out_h, label_h = mx.nd.asnumpy_all(loss, out, label)
            cum_loss += float(loss_h.sum())
            correct += int((out_h.argmax(1) == label_h).sum())
            total += len(label)
        print("epoch %d: loss %.4f acc %.3f"
              % (epoch, cum_loss / total, correct / total))


if __name__ == "__main__":
    main()
