#!/usr/bin/env python
"""MNIST with the Module API (reference example/image-classification/
train_mnist.py workflow). Uses mx.io.MNISTIter when the idx files are
present (--data-dir), otherwise a synthetic stand-in so the script runs
anywhere."""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _common import maybe_force_cpu  # noqa: E402
maybe_force_cpu()

import logging
logging.basicConfig(level=logging.INFO)

import numpy as np
import mxnet_tpu as mx


def get_iters(args):
    img = os.path.join(args.data_dir, "train-images-idx3-ubyte")
    if os.path.exists(img):
        train = mx.io.MNISTIter(
            image=img,
            label=os.path.join(args.data_dir, "train-labels-idx1-ubyte"),
            batch_size=args.batch_size, shuffle=True)
        val = mx.io.MNISTIter(
            image=os.path.join(args.data_dir, "t10k-images-idx3-ubyte"),
            label=os.path.join(args.data_dir, "t10k-labels-idx1-ubyte"),
            batch_size=args.batch_size)
        return train, val
    print("no MNIST at %s - using a synthetic stand-in" % args.data_dir)
    rng = np.random.RandomState(0)
    n = 2048
    y = rng.randint(0, 10, n).astype(np.float32)
    X = rng.rand(n, 1, 28, 28).astype(np.float32) * 0.1
    for i in range(n):  # class-dependent blob so the task is learnable
        c = int(y[i])
        X[i, 0, 2 * c:2 * c + 6, 4:24] += 0.8
    cut = n - 512
    return (mx.io.NDArrayIter(X[:cut], y[:cut], args.batch_size, shuffle=True),
            mx.io.NDArrayIter(X[cut:], y[cut:], args.batch_size))


def mlp_symbol():
    data = mx.sym.Variable("data")
    net = mx.sym.Flatten(data)
    net = mx.sym.FullyConnected(net, num_hidden=128, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=64, name="fc2")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=10, name="fc3")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--device", default="auto",
                    choices=["auto", "cpu"])
    ap.add_argument("--data-dir", default="data/mnist")
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--num-epochs", type=int, default=5)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--kv-store", default="local",
                    help="'tpu_sync' fuses the whole step on TPU")
    args = ap.parse_args()

    train, val = get_iters(args)
    mod = mx.mod.Module(mlp_symbol())
    mod.fit(train, eval_data=val, num_epoch=args.num_epochs,
            kvstore=args.kv_store, optimizer="sgd",
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9},
            initializer=mx.initializer.Xavier(),
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 50))
    val.reset()
    print("final validation:", mod.score(val, "acc"))


if __name__ == "__main__":
    main()
