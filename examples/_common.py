"""Shared example bootstrap: honor --device cpu / --device=cpu BEFORE any
jax backend use (the env var is overridden by sitecustomize; only
jax.config works)."""
import sys


def maybe_force_cpu(argv=None):
    argv = sys.argv if argv is None else argv
    i = argv.index("--device") if "--device" in argv else -1
    if "--device=cpu" in argv or (i >= 0 and argv[i + 1:i + 2] == ["cpu"]):
        import jax
        jax.config.update("jax_platforms", "cpu")
        # pure_callback custom ops (e.g. train_rcnn's proposal/target ops)
        # re-enter jax from the callback thread; with async CPU dispatch
        # that deadlocks on thread-pool starvation when cores are scarce.
        # Must be set before the CPU client exists.
        jax.config.update("jax_cpu_enable_async_dispatch", False)


def pick_ctx():
    """mx.tpu() when a real accelerator backend resolved, else mx.cpu()."""
    import jax
    import mxnet_tpu as mx
    return mx.tpu() if jax.devices()[0].platform != "cpu" else mx.cpu()


def check_improved(metric_name, values, lower_is_better=True):
    """Exit nonzero when a multi-epoch run did not improve; a single
    epoch can't self-compare and just reports the value."""
    if len(values) < 2:
        return
    ok = values[-1] < values[0] if lower_is_better else         values[-1] > values[0]
    if not ok:
        raise SystemExit("%s did not improve: %s" % (metric_name, values))
