"""Shared example bootstrap: honor --device cpu / --device=cpu BEFORE any
jax backend use (the env var is overridden by sitecustomize; only
jax.config works)."""
import sys


def maybe_force_cpu(argv=None):
    argv = sys.argv if argv is None else argv
    i = argv.index("--device") if "--device" in argv else -1
    if "--device=cpu" in argv or (i >= 0 and argv[i + 1:i + 2] == ["cpu"]):
        import jax
        jax.config.update("jax_platforms", "cpu")
