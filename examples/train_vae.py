#!/usr/bin/env python
"""Variational autoencoder (reference example/autoencoder + the VAE
tutorial workflow): dense encoder to (mu, logvar), reparameterized
sample z = mu + eps * exp(0.5 * logvar), dense decoder, trained on the
ELBO (reconstruction BCE + KL to the unit Gaussian).

TPU notes: the eps draw happens INSIDE autograd.record through the
stateful RNG facade, so the whole step — sampling included — compiles
into the hybridized program with a threaded PRNG key; the KL term uses
only fused elementwise ops.

Runs on synthetic blob images (no dataset download); success = ELBO
decreasing across epochs.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _common import maybe_force_cpu, pick_ctx, check_improved  # noqa: E402
maybe_force_cpu()

import logging
logging.basicConfig(level=logging.INFO)

import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import gluon, autograd


class VAE(gluon.HybridBlock):
    def __init__(self, n_hidden=128, n_latent=8, n_out=256, **kw):
        super().__init__(**kw)
        self.n_latent = n_latent
        with self.name_scope():
            self.enc = gluon.nn.HybridSequential()
            self.enc.add(gluon.nn.Dense(n_hidden, activation="relu"))
            self.enc.add(gluon.nn.Dense(n_latent * 2))
            self.dec = gluon.nn.HybridSequential()
            self.dec.add(gluon.nn.Dense(n_hidden, activation="relu"))
            self.dec.add(gluon.nn.Dense(n_out, activation="sigmoid"))

    def hybrid_forward(self, F, x):
        h = self.enc(x)
        mu = F.slice_axis(h, axis=1, begin=0, end=self.n_latent)
        logvar = F.slice_axis(h, axis=1, begin=self.n_latent, end=None)
        eps = F._random_normal_like(mu)
        z = mu + F.exp(0.5 * logvar) * eps
        y = self.dec(z)
        # KL(q(z|x) || N(0, I)) per sample
        kl = -0.5 * F.sum(1 + logvar - mu * mu - F.exp(logvar), axis=1)
        return y, kl


def synthetic_images(n, rng, side=16):
    yy, xx = np.mgrid[0:side, 0:side].astype(np.float32) / (side - 1)
    out = np.empty((n, side * side), np.float32)
    for i in range(n):
        cx, cy = rng.rand(2) * 0.6 + 0.2
        r = rng.rand() * 0.1 + 0.08
        img = np.exp(-(((xx - cx) ** 2 + (yy - cy) ** 2) / (2 * r * r)))
        out[i] = img.ravel()
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--epochs", type=int, default=5)
    ap.add_argument("--num-samples", type=int, default=512)
    ap.add_argument("--n-latent", type=int, default=8)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--device", default=None, help="cpu to force CPU")
    args = ap.parse_args()

    ctx = pick_ctx()
    rng = np.random.RandomState(0)
    X = synthetic_images(args.num_samples, rng)
    it = mx.io.NDArrayIter(X, batch_size=args.batch_size, shuffle=True)

    net = VAE(n_latent=args.n_latent, n_out=X.shape[1])
    net.initialize(mx.initializer.Xavier(), ctx=ctx)
    net.hybridize()
    bce = gluon.loss.SigmoidBinaryCrossEntropyLoss(from_sigmoid=True)
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})

    elbos = []
    for epoch in range(args.epochs):
        it.reset()
        losses = []
        for batch in it:
            x = batch.data[0].as_in_context(ctx)
            with autograd.record():
                y, kl = net(x)
                rec = bce(y, x) * X.shape[1]
                loss = rec + kl
            loss.backward()
            trainer.step(args.batch_size)
            losses.append(float(loss.mean().asnumpy()))
        elbos.append(float(np.mean(losses)))
        logging.info("epoch %d  -ELBO %.3f", epoch, elbos[-1])

    # decode fresh prior samples — the generative direction works
    z = mx.nd.random.normal(shape=(4, args.n_latent), ctx=ctx)
    samples = net.dec(z)
    assert samples.shape == (4, X.shape[1])
    check_improved("-ELBO", elbos)
    print("vae OK: -ELBO %.3f -> %.3f" % (elbos[0], elbos[-1]))


if __name__ == "__main__":
    main()
