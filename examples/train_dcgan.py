#!/usr/bin/env python
"""DCGAN (reference example/gluon/dc_gan/dcgan.py workflow): transposed-
convolution generator vs strided-conv discriminator, trained
adversarially with the non-saturating BCE objective.

TPU notes: both nets hybridize (each becomes one jitted XLA program);
the generator's Conv2DTranspose layers lower to
``lax.conv_general_dilated`` with lhs_dilation (MXU path), and each
optimization step runs discriminator-on-real, discriminator-on-fake,
and generator updates back to back on device.

Without --data, trains on synthetic two-moons-style 32x32 blob images
so the script runs anywhere; success = discriminator loss staying away
from 0 while the generator's fooling rate rises above chance.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _common import maybe_force_cpu, pick_ctx  # noqa: E402
maybe_force_cpu()

import logging
logging.basicConfig(level=logging.INFO)

import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import gluon, autograd


def build_generator(ngf=32, nc=1):
    """z (N, nz, 1, 1) -> image (N, nc, 32, 32) in [-1, 1]."""
    net = gluon.nn.HybridSequential(prefix="gen_")
    with net.name_scope():
        # 1x1 -> 4x4 -> 8x8 -> 16x16 -> 32x32
        net.add(gluon.nn.Conv2DTranspose(ngf * 4, 4, strides=1, padding=0,
                                         use_bias=False))
        net.add(gluon.nn.BatchNorm(), gluon.nn.Activation("relu"))
        net.add(gluon.nn.Conv2DTranspose(ngf * 2, 4, strides=2, padding=1,
                                         use_bias=False))
        net.add(gluon.nn.BatchNorm(), gluon.nn.Activation("relu"))
        net.add(gluon.nn.Conv2DTranspose(ngf, 4, strides=2, padding=1,
                                         use_bias=False))
        net.add(gluon.nn.BatchNorm(), gluon.nn.Activation("relu"))
        net.add(gluon.nn.Conv2DTranspose(nc, 4, strides=2, padding=1,
                                         use_bias=False))
        net.add(gluon.nn.Activation("tanh"))
    return net


def build_discriminator(ndf=32, leak=0.2):
    """image (N, nc, 32, 32) -> logit (N, 1)."""
    net = gluon.nn.HybridSequential(prefix="disc_")
    with net.name_scope():
        net.add(gluon.nn.Conv2D(ndf, 4, strides=2, padding=1,
                                use_bias=False))
        net.add(gluon.nn.LeakyReLU(leak))
        net.add(gluon.nn.Conv2D(ndf * 2, 4, strides=2, padding=1,
                                use_bias=False))
        net.add(gluon.nn.BatchNorm(), gluon.nn.LeakyReLU(leak))
        net.add(gluon.nn.Conv2D(ndf * 4, 4, strides=2, padding=1,
                                use_bias=False))
        net.add(gluon.nn.BatchNorm(), gluon.nn.LeakyReLU(leak))
        net.add(gluon.nn.Conv2D(1, 4, strides=1, padding=0,
                                use_bias=False))
        net.add(gluon.nn.Flatten())
    return net


def synthetic_images(n, rng):
    """Smooth blob images in [-1, 1] — enough structure that a
    discriminator can tell them from early generator noise."""
    yy, xx = np.mgrid[0:32, 0:32].astype(np.float32) / 31.0
    imgs = np.empty((n, 1, 32, 32), np.float32)
    for i in range(n):
        cx, cy = rng.rand(2) * 0.6 + 0.2
        r = rng.rand() * 0.15 + 0.1
        img = np.exp(-(((xx - cx) ** 2 + (yy - cy) ** 2) / (2 * r * r)))
        imgs[i, 0] = img * 2.0 - 1.0
    return imgs


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--nz", type=int, default=64)
    ap.add_argument("--lr", type=float, default=2e-4)
    ap.add_argument("--num-samples", type=int, default=256)
    ap.add_argument("--ngf", type=int, default=32)
    ap.add_argument("--ndf", type=int, default=32)
    ap.add_argument("--device", default=None, help="cpu to force CPU")
    args = ap.parse_args()
    if args.epochs < 1:
        ap.error("--epochs must be >= 1")

    ctx = pick_ctx()
    rng = np.random.RandomState(0)
    real_images = synthetic_images(args.num_samples, rng)
    it = mx.io.NDArrayIter(real_images, batch_size=args.batch_size,
                           shuffle=True)

    gen = build_generator(args.ngf)
    disc = build_discriminator(args.ndf)
    gen.initialize(mx.initializer.Normal(0.02), ctx=ctx)
    disc.initialize(mx.initializer.Normal(0.02), ctx=ctx)
    gen.hybridize()
    disc.hybridize()

    loss_fn = gluon.loss.SigmoidBinaryCrossEntropyLoss()
    # the small-capacity discriminator wins easily on the synthetic set;
    # classic balancing — slower D, two G updates per D update — keeps
    # the adversarial signal alive (reference dcgan.py uses 1:1 at equal
    # lr on CIFAR-scale data)
    g_tr = gluon.Trainer(gen.collect_params(), "adam",
                         {"learning_rate": args.lr, "beta1": 0.5})
    d_tr = gluon.Trainer(disc.collect_params(), "adam",
                         {"learning_rate": args.lr * 0.5, "beta1": 0.5})

    ones = mx.nd.ones((args.batch_size,), ctx=ctx)
    zeros = mx.nd.zeros((args.batch_size,), ctx=ctx)

    fool_rate = 0.0
    for epoch in range(args.epochs):
        it.reset()
        d_losses, g_losses, fooled = [], [], []
        for batch in it:
            real = batch.data[0].as_in_context(ctx)
            z = mx.nd.array(rng.randn(args.batch_size, args.nz, 1, 1)
                            .astype(np.float32), ctx=ctx)
            # --- discriminator: real up, fake down
            with autograd.record():
                out_real = disc(real).reshape((-1,))
                fake = gen(z)
                out_fake = disc(fake.detach()).reshape((-1,))
                d_loss = loss_fn(out_real, ones) + loss_fn(out_fake, zeros)
            d_loss.backward()
            d_tr.step(args.batch_size)
            # --- generator: make disc call fakes real (x2)
            for _ in range(2):
                with autograd.record():
                    out = disc(gen(z)).reshape((-1,))
                    g_loss = loss_fn(out, ones)
                g_loss.backward()
                g_tr.step(args.batch_size)
            # one device->host sync for all three (mxlint MXL103)
            d_h, g_h, f_h = mx.nd.asnumpy_all(
                d_loss.mean(), g_loss.mean(),
                (out.sigmoid() > 0.5).mean())
            d_losses.append(float(d_h))
            g_losses.append(float(g_h))
            fooled.append(float(f_h))
        fool_rate = float(np.mean(fooled))
        logging.info("epoch %d  d_loss %.3f  g_loss %.3f  fool-rate %.2f",
                     epoch, np.mean(d_losses), np.mean(g_losses),
                     fool_rate)
    d_final = float(np.mean(d_losses))
    if not np.isfinite(d_final) or d_final < 0.05:
        raise SystemExit("adversarial game collapsed: d_loss %.4f"
                         % d_final)
    print("dcgan OK: final fool-rate %.2f d_loss %.3f"
          % (fool_rate, d_final))
    return fool_rate


if __name__ == "__main__":
    main()
