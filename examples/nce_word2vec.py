#!/usr/bin/env python
"""Word2vec-style training with NCE loss and zipfian negative sampling
(reference example/nce-loss/wordvec.py + nce.py workflow).

The NCE head follows the reference construction: embed the [positive |
negative] candidate ids, dot them against the context vector, and train
logistic targets through LogisticRegressionOutput. Negatives come from
`_sample_unique_zipfian` (the sampled-softmax proposal distribution,
reference unique_sample_op.h) instead of the reference's host-side
alias-table sampler — the draw runs on device.

Synthetic skip-gram data: a vocabulary with planted co-occurrence
structure (word w co-occurs with w^1), so the learned embeddings are
testable: after training, the embedding of w should be closer to w^1
than to random words.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _common import maybe_force_cpu, pick_ctx  # noqa: E402
maybe_force_cpu()

import logging
logging.basicConfig(level=logging.INFO)

import numpy as np
import mxnet_tpu as mx


def nce_symbol(vocab_size, dim, num_neg):
    """Context word -> dot with [pos | negs] embeddings -> logistic."""
    center = mx.sym.Variable("data")              # (N,) center word ids
    cands = mx.sym.Variable("cands")              # (N, 1+num_neg) ids
    targets = mx.sym.Variable("softmax_label")    # (N, 1+num_neg) 0/1
    embed_w = mx.sym.Variable("embed_weight")
    ctx_vec = mx.sym.Embedding(center, weight=embed_w,
                               input_dim=vocab_size, output_dim=dim,
                               name="ctx_embed")
    cand_vec = mx.sym.Embedding(cands, weight=embed_w,
                                input_dim=vocab_size, output_dim=dim,
                                name="cand_embed")   # (N, 1+neg, dim)
    ctx3 = mx.sym.Reshape(ctx_vec, shape=(-1, 1, dim))
    logits = mx.sym.sum(mx.sym.broadcast_mul(ctx3, cand_vec), axis=2)
    return mx.sym.LogisticRegressionOutput(logits, targets, name="nce")


def make_batches(vocab, batch, num_neg, steps, seed=0):
    """Skip-gram pairs (w, w^1) + device-side zipfian negatives.

    Center words are drawn LOG-UNIFORMLY, matching the zipfian noise
    distribution — the word2vec setup (noise ~ corpus frequency): a
    mismatched uniform corpus would bias low ids toward pure-negative
    roles and stall the contrastive signal."""
    rng = np.random.RandomState(seed)
    for _ in range(steps):
        center = np.minimum(
            np.exp(rng.uniform(0, np.log(vocab), batch)).astype("i8") - 1,
            vocab - 1)
        pos = center ^ 1                      # planted co-occurrence
        negs, _ = mx.nd.invoke("_sample_unique_zipfian", [],
                               {"range_max": vocab,
                                "shape": (batch, num_neg)})
        negs = negs.asnumpy().astype("i8")
        # zipfian favors small ids, so low-id partners WILL be drawn as
        # "negatives"; shift accidental hits off the true positive (the
        # reference trainers likewise avoid poisoning the pos target)
        hit = negs == pos[:, None]
        negs[hit] = (negs[hit] + vocab // 2) % vocab
        cands = np.concatenate([pos[:, None], negs], axis=1)
        targets = np.zeros((batch, 1 + num_neg), "f4")
        targets[:, 0] = 1.0
        yield center.astype("f4"), cands.astype("f4"), targets


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--vocab", type=int, default=512)
    p.add_argument("--dim", type=int, default=32)
    p.add_argument("--num-neg", type=int, default=15)
    p.add_argument("--batch-size", type=int, default=256)
    p.add_argument("--steps", type=int, default=300)
    p.add_argument("--lr", type=float, default=0.2)
    p.add_argument("--device", default=None)
    args = p.parse_args()
    if args.vocab % 2:
        p.error("--vocab must be even (words are paired by id^1)")

    dev = pick_ctx()
    sym = nce_symbol(args.vocab, args.dim, args.num_neg)
    ex = sym.simple_bind(dev, data=(args.batch_size,),
                         cands=(args.batch_size, 1 + args.num_neg),
                         softmax_label=(args.batch_size, 1 + args.num_neg),
                         grad_req={"embed_weight": "write", "data": "null",
                                   "cands": "null", "softmax_label": "null"})
    rng = np.random.RandomState(1)
    ex.arg_dict["embed_weight"][:] = mx.nd.array(
        rng.uniform(-0.3, 0.3, (args.vocab, args.dim)).astype("f4"),
        ctx=dev)

    losses = []
    for i, (center, cands, targets) in enumerate(
            make_batches(args.vocab, args.batch_size, args.num_neg,
                         args.steps)):
        ex.forward(is_train=True, data=mx.nd.array(center, ctx=dev),
                   cands=mx.nd.array(cands, ctx=dev),
                   softmax_label=mx.nd.array(targets, ctx=dev))
        probs = ex.outputs[0]
        # logistic NLL for monitoring
        pn = probs.asnumpy()
        eps = 1e-7
        nll = -np.mean(targets * np.log(pn + eps)
                       + (1 - targets) * np.log(1 - pn + eps))
        losses.append(nll)
        ex.backward()
        g = ex.grad_dict["embed_weight"]
        ex.arg_dict["embed_weight"] -= args.lr * g
        if i % 100 == 0:
            logging.info("step %d nce-nll %.4f", i, nll)

    print("nll first->last: %.4f -> %.4f" % (losses[0], losses[-1]))
    assert losses[-1] < losses[0] * 0.95, "NCE training did not improve"

    # embedding sanity: planted partner is the nearest neighbour more
    # often than chance
    W = ex.arg_dict["embed_weight"].asnumpy()
    Wn = W / (np.linalg.norm(W, axis=1, keepdims=True) + 1e-8)
    sims = Wn @ Wn.T
    np.fill_diagonal(sims, -np.inf)
    hits = float(np.mean(sims.argmax(axis=1) == (
        np.arange(args.vocab) ^ 1)))
    print("partner-nearest-neighbour rate: %.2f (chance %.4f)"
          % (hits, 1.0 / args.vocab))
    assert hits > 0.2, "embeddings did not capture co-occurrence"


if __name__ == "__main__":
    main()
