#!/usr/bin/env python
"""Two-tower retrieval trainer over the sharded embedding subsystem
(mxnet_tpu.embed) — the PR-15 end-to-end demo.

Pure-embedding matrix factorization: a USER table and an ITEM table,
dot-product score, L2 loss on synthetic low-rank ratings with Zipf-
skewed traffic (the access pattern that makes a hot-row cache work).
Every parameter gets a canonical sparse gradient, which is what makes
the cross-path bitwise checks below possible at all.

Three training paths over the SAME stream, all landing bitwise-equal
final tables:

1. ``--mesh 1``     — 1-rank dense reference (``jnp.take`` VJP).
2. ``--mesh dp,tp`` — tables row-sharded over the mesh
   (:class:`ShardedEmbedding`), lookups via the all-to-all core inside
   ``shard_map``; the autodiff transpose scatter-adds gradient
   contributions in global batch order, so the update is bitwise-equal
   to path 1 (the chip-free fleet gate).
3. ``--capacity N`` — hot-row cache + host spill
   (:class:`HotRowCache`): the device holds N rows, the logical table
   can exceed ``--host-budget-mb``-bounded host memory by lazy row
   init, and per-row update arithmetic is slot-independent — so the
   final table is bitwise-equal to paths 1 and 2 at ANY capacity.

Per ``--window`` steps the trainer publishes host-held telemetry
(``embed/cache_hit_rate``, ``embed/spill_bytes``,
``ddp/sparse_comm_bytes`` — zero extra d2h), and at the end exports
the trained towers as a format_version-6 ``.mxtpu`` recommend artifact
(serve it: ``python -m mxnet_tpu.tools.serve --artifact out.mxtpu``,
then ``POST /v1/recommend``).

``--recordio PREFIX`` swaps the in-process generator for a streamed
feed: the interactions come from a ``tools/make_recordio.py twotower``
shard set via :class:`mxnet_tpu.data.ShardedRecordStream`
(docs/data.md) into the same up-front arrays, so all three paths stay
bitwise-comparable over streamed data too.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def zipf_ids(rng, n, rows, a=1.2):
    """Zipf-skewed row ids in [0, rows) — heavier head for smaller a-1."""
    ids = rng.zipf(a, size=n)
    return ((ids - 1) % rows).astype("int64")


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--users", type=int, default=512)
    p.add_argument("--items", type=int, default=128)
    p.add_argument("--dim", type=int, default=16)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--steps", type=int, default=60)
    p.add_argument("--lr", type=float, default=0.5)
    p.add_argument("--zipf", type=float, default=1.3)
    p.add_argument("--mesh", default="2,2",
                   help="'1' for the dense 1-rank path, or 'DP,TP' "
                        "(e.g. 2,2) for the sharded mesh path")
    p.add_argument("--capacity", type=int, default=96,
                   help="hot-row cache rows for the cache+spill path "
                        "(0 disables that path)")
    p.add_argument("--host-budget-mb", type=float, default=0.0,
                   help="spill-store budget; 0 = unbounded")
    p.add_argument("--window", type=int, default=20,
                   help="telemetry publish window (steps)")
    p.add_argument("--out", default=None,
                   help="write the trained towers as a recommend "
                        ".mxtpu artifact")
    p.add_argument("--recordio", default=None, metavar="PREFIX",
                   help="stream the (user, item, rating) interactions "
                        "from a tools/make_recordio.py twotower shard "
                        "set (PREFIX-00000.rec ...) instead of "
                        "generating them in-process; --users/--items "
                        "must cover the packed id range")
    p.add_argument("--devices", type=int, default=8)
    p.add_argument("--device", default=None)
    args = p.parse_args()

    if args.device != "tpu":
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=%d" % args.devices)
        from _common import maybe_force_cpu
        maybe_force_cpu(["--device", "cpu"])

    import time

    import numpy as np
    import jax
    import jax.numpy as jnp
    import mxnet_tpu  # noqa: F401  (platform pinning, registry)
    from mxnet_tpu import telemetry
    from mxnet_tpu.embed import (HotRowCache, ShardedEmbedding,
                                 SpillStore, row_init)
    from mxnet_tpu.parallel import make_mesh
    from mxnet_tpu.parallel.ddp import SparseBucket

    U, I, D, B = args.users, args.items, args.dim, args.batch_size
    rng = np.random.RandomState(0)
    if args.recordio:
        # streaming feed (docs/data.md): fill the SAME up-front
        # (steps, B) arrays all three paths consume from a
        # make_recordio twotower shard set, so the cross-path bitwise
        # checks hold unchanged for streamed interactions.
        import glob

        from mxnet_tpu import recordio as rio
        from mxnet_tpu.data import ShardedRecordStream
        recs = sorted(glob.glob(args.recordio + "-*.rec"))
        if not recs:
            raise SystemExit("no shards match %s-*.rec — pack one with "
                             "tools/make_recordio.py twotower"
                             % args.recordio)
        stream = ShardedRecordStream(recs, shuffle=True, seed=0)
        need = args.steps * B
        triples = np.empty((need, 3), dtype="f4")
        got = 0
        while got < need:
            before = got
            for rec in stream:
                _, payload = rio.unpack(rec)
                triples[got] = np.frombuffer(payload, dtype="<f4", count=3)
                got += 1
                if got == need:
                    break
            if got == before:
                raise SystemExit("empty recordio set: %r" % recs)
            if got < need:
                stream.next_epoch()   # set smaller than steps*B: reuse
        u_ids = triples[:, 0].astype("int64").reshape(args.steps, B)
        i_ids = triples[:, 1].astype("int64").reshape(args.steps, B)
        if u_ids.max() >= U or i_ids.max() >= I:
            raise SystemExit(
                "packed ids exceed --users/--items (%d/%d): pass at "
                "least --users %d --items %d"
                % (U, I, int(u_ids.max()) + 1, int(i_ids.max()) + 1))
        ratings = triples[:, 2].reshape(args.steps, B).copy()
    else:
        # learnable signal: ratings from a hidden low-rank model
        gt_u = rng.randn(U, 8).astype("f4") / np.sqrt(8)
        gt_i = rng.randn(I, 8).astype("f4") / np.sqrt(8)
        u_ids = zipf_ids(rng, args.steps * B, U, args.zipf).reshape(
            args.steps, B)
        i_ids = zipf_ids(rng, args.steps * B, I, args.zipf).reshape(
            args.steps, B)
        ratings = ((gt_u[u_ids] * gt_i[i_ids]).sum(-1)
                   + 0.01 * rng.randn(args.steps, B)).astype("f4")
    lr = np.float32(args.lr)

    # -- path 1/2: dense or mesh-sharded tables ----------------------------
    shape = [int(s) for s in args.mesh.split(",")]
    if len(shape) == 1 and shape[0] == 1:
        mesh, axes = None, None
    else:
        mesh = make_mesh({"dp": shape[0], "tp": shape[1]})
        axes = ("dp", "tp")
    emb_u = ShardedEmbedding(U, D, mesh=mesh, axis_names=axes, seed=1)
    emb_i = ShardedEmbedding(I, D, mesh=mesh, axis_names=axes, seed=2)

    def loss_core(u_tab, i_tab, u, i, r, n_global):
        uv = emb_u.lookup(u_tab, u)
        iv = emb_i.lookup(i_tab, i)
        err = (uv * iv).sum(-1) - r
        return (err ** 2).sum() / n_global

    if mesh is None:
        def step_fn(u_tab, i_tab, u, i, r):
            loss, (gu, gi) = jax.value_and_grad(
                loss_core, argnums=(0, 1))(u_tab, i_tab, u, i, r, B)
            return u_tab - lr * gu, i_tab - lr * gi, loss
    else:
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P
        ax = emb_u.axis_name

        def sharded_step(u_tab, i_tab, u, i, r):
            # grad of the LOCAL partial (cotangent 1 per shard; every
            # rank's contribution reaches the owner stripe through the
            # all-to-all transpose); psum only the REPORTED loss —
            # psum inside the grad would multiply cotangents by the
            # axis size
            loss, (gu, gi) = jax.value_and_grad(
                loss_core, argnums=(0, 1))(u_tab, i_tab, u, i, r, B)
            return (u_tab - lr * gu, i_tab - lr * gi,
                    jax.lax.psum(loss, ax))

        step_fn = shard_map(
            sharded_step, mesh=mesh,
            in_specs=(emb_u.table_spec, emb_i.table_spec,
                      P(ax), P(ax), P(ax)),
            out_specs=(emb_u.table_spec, emb_i.table_spec, P()),
            check_rep=False)
    step_fn = jax.jit(step_fn, donate_argnums=(0, 1))

    # host-held sparse-DDP exchange plan for telemetry: what a
    # dp-replicated variant of these tables would move per step,
    # coalesced vs densified (parallel/ddp.py sparse bucket kind)
    n_ranks = 1 if mesh is None else emb_u.num_shards
    sparse_plan = [SparseBucket("user", B // max(1, n_ranks), D, U),
                   SparseBucket("item", B // max(1, n_ranks), D, I)]
    sparse_comm = sum(sb.comm_bytes(n_ranks) for sb in sparse_plan)
    densified = sum(sb.densified_bytes() for sb in sparse_plan)

    u_tab = emb_u.device_put(emb_u.init())
    i_tab = emb_i.device_put(emb_i.init())
    losses, t0 = [], time.perf_counter()
    for s in range(args.steps):
        u_tab, i_tab, loss = step_fn(u_tab, i_tab,
                                     jnp.asarray(u_ids[s]),
                                     jnp.asarray(i_ids[s]),
                                     jnp.asarray(ratings[s]))
        if (s + 1) % args.window == 0:
            losses.append(float(loss))    # ONE d2h per window
            telemetry.publish_window(
                steps=args.window,
                window_s=time.perf_counter() - t0,
                examples=args.window * B, global_step=s + 1,
                source="twotower/%s" % ("mesh" if mesh else "dense"),
                ddp={"buckets": len(sparse_plan),
                     "comm_bytes": 0, "overlap_ms": 0.0,
                     "sparse_comm_bytes": sparse_comm * args.window})
            t0 = time.perf_counter()
    mesh_u = np.asarray(jax.device_get(u_tab))[:U]
    mesh_i = np.asarray(jax.device_get(i_tab))[:I]
    print("[%s] loss %.4f -> %.4f  (sparse comm %.1f KiB/step, "
          "densified %.1f KiB, %.0fx)"
          % ("mesh %dx%d" % tuple(shape) if mesh else "dense",
             losses[0], losses[-1], sparse_comm / 1024,
             densified / 1024, densified / max(1, sparse_comm)))
    assert losses[-1] < losses[0], "two-tower training did not improve"

    # -- path 3: hot-row cache + host spill --------------------------------
    if args.capacity > 0:
        budget = (int(args.host_budget_mb * (1 << 20))
                  if args.host_budget_mb > 0 else None)
        store_u = SpillStore(U, D, seed=1, budget_bytes=budget)
        store_i = SpillStore(I, D, seed=2, budget_bytes=budget)
        cache_u = HotRowCache(store_u, args.capacity)
        cache_i = HotRowCache(store_i, min(args.capacity, I))

        @jax.jit
        def cache_step(u_buf, i_buf, us, isl, r):
            uv = u_buf[us]
            iv = i_buf[isl]
            err = (uv * iv).sum(-1) - r
            loss = (err ** 2).sum() / r.shape[0]
            d = (2.0 / r.shape[0]) * err
            # coalesce per row FIRST (position-ordered scatter-add: the
            # same left fold as the dense take VJP), THEN one update per
            # row — bitwise-equal to the dense path, slot-independent
            gu = jnp.zeros_like(u_buf).at[us].add(d[:, None] * iv)
            gi = jnp.zeros_like(i_buf).at[isl].add(d[:, None] * uv)
            return u_buf - lr * gu, i_buf - lr * gi, loss

        cache_step = jax.jit(cache_step, donate_argnums=(0, 1))
        last_spill = 0
        closses, t0 = [], time.perf_counter()
        for s in range(args.steps):
            us = cache_u.ensure(u_ids[s])
            isl = cache_i.ensure(i_ids[s])
            cache_u.buf, cache_i.buf, loss = cache_step(
                cache_u.buf, cache_i.buf, us, isl,
                jnp.asarray(ratings[s]))
            cache_u.note_updated(u_ids[s])
            cache_i.note_updated(i_ids[s])
            if (s + 1) % args.window == 0:
                closses.append(float(loss))
                spill = (cache_u.spill_bytes + cache_i.spill_bytes)
                telemetry.publish_window(
                    steps=args.window,
                    window_s=time.perf_counter() - t0,
                    examples=args.window * B, global_step=s + 1,
                    source="twotower/cache",
                    embed={"hit_rate": cache_u.hit_rate(),
                           "spill_bytes": spill - last_spill})
                last_spill = spill
                t0 = time.perf_counter()
        cache_u.flush()
        cache_i.flush()
        fin_u = store_u.peek(np.arange(U))
        fin_i = store_i.peek(np.arange(I))
        st = cache_u.stats()
        print("[cache %d] loss %.4f -> %.4f  (hit rate %.3f, spilled "
              "%d KiB, host-resident %d/%d KiB)"
              % (args.capacity, closses[0], closses[-1], st["hit_rate"],
                 st["spill_bytes"] // 1024,
                 st["host_resident_bytes"] // 1024,
                 st["logical_bytes"] // 1024))
        exact_u = np.array_equal(fin_u, mesh_u)
        exact_i = np.array_equal(fin_i, mesh_i)
        print("bitwise cache-vs-%s: user=%s item=%s"
              % ("mesh" if mesh else "dense", exact_u, exact_i))
        assert exact_u and exact_i, (
            "cache+spill final tables diverged from the reference path")
        out_u, out_i = fin_u, fin_i
    else:
        out_u, out_i = mesh_u, mesh_i

    if args.out:
        from mxnet_tpu.embed.serve import export_recommend
        meta = export_recommend(out_u, out_i, args.out,
                                max_ids=64, k=10)
        print("exported %s (format_version %d, %dx%d users, %d items)"
              % (args.out, meta["format_version"], U, D, I))
    print("two-tower OK")


if __name__ == "__main__":
    main()
