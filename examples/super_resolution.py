#!/usr/bin/env python
"""Single-image super-resolution (reference example/gluon/
super_resolution.py workflow): the ESPCN sub-pixel CNN — conv stack +
depth_to_space (PixelShuffle) upscaling — trained with L2 loss on the
hybridize() imperative path, PSNR reported per epoch.

--data points at a directory of images (the reference uses BSDS300);
without it, synthetic smooth images are generated (band-limited noise)
so the script trains anywhere and PSNR measurably rises.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _common import maybe_force_cpu, pick_ctx, check_improved  # noqa: E402
maybe_force_cpu()

import logging
logging.basicConfig(level=logging.INFO)

import math
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import gluon, autograd


class SuperResolutionNet(gluon.HybridBlock):
    def __init__(self, upscale_factor, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.conv1 = gluon.nn.Conv2D(64, (5, 5), padding=(2, 2),
                                         activation="relu")
            self.conv2 = gluon.nn.Conv2D(64, (3, 3), padding=(1, 1),
                                         activation="relu")
            self.conv3 = gluon.nn.Conv2D(32, (3, 3), padding=(1, 1),
                                         activation="relu")
            self.conv4 = gluon.nn.Conv2D(upscale_factor ** 2, (3, 3),
                                         padding=(1, 1))
        self.upscale_factor = upscale_factor

    def hybrid_forward(self, F, x):
        x = self.conv4(self.conv3(self.conv2(self.conv1(x))))
        # PixelShuffle: (B, r^2, H, W) -> (B, 1, H*r, W*r)
        return F.depth_to_space(x, block_size=self.upscale_factor)


def synthetic_pairs(n=128, size=32, factor=2, seed=0):
    """Band-limited random images: downsample is information-lossy but
    learnable."""
    rng = np.random.RandomState(seed)
    hi = []
    for _ in range(n):
        freq = rng.randn(6, 6)
        img = np.zeros((size * factor, size * factor), np.float32)
        xs = np.linspace(0, 2 * np.pi, size * factor)
        for i in range(6):
            for j in range(6):
                img += freq[i, j] * np.outer(np.sin((i + 1) * xs / 2),
                                             np.cos((j + 1) * xs / 2))
        img = (img - img.min()) / (np.ptp(img) + 1e-6)
        hi.append(img.astype(np.float32))
    hi = np.stack(hi)[:, None]                      # (N, 1, H*r, W*r)
    lo = hi[:, :, ::factor, ::factor]               # nearest downsample
    return lo, hi


def load_dir(path, size=64, factor=2):
    import cv2
    his = []
    for f in sorted(os.listdir(path)):
        img = cv2.imread(os.path.join(path, f))
        if img is None:
            continue
        y = cv2.cvtColor(img, cv2.COLOR_BGR2YCrCb)[:, :, 0]
        y = cv2.resize(y, (size * factor, size * factor))
        his.append((y / 255.0).astype(np.float32))
    hi = np.stack(his)[:, None]
    return hi[:, :, ::factor, ::factor], hi


def psnr(pred, target):
    mse = float(np.mean((pred - target) ** 2))
    return 10 * math.log10(1.0 / max(mse, 1e-10))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--data", default=None, help="directory of images")
    p.add_argument("--upscale-factor", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--epochs", type=int, default=6)
    p.add_argument("--lr", type=float, default=0.001)
    p.add_argument("--device", default=None)
    args = p.parse_args()

    ctx = pick_ctx()
    lo, hi = (load_dir(args.data, factor=args.upscale_factor)
              if args.data else synthetic_pairs(factor=args.upscale_factor))
    it = mx.io.NDArrayIter(lo, hi, batch_size=args.batch_size,
                           shuffle=True, label_name="label")

    net = SuperResolutionNet(args.upscale_factor)
    net.initialize(mx.initializer.Orthogonal(), ctx=ctx)
    net.hybridize(static_alloc=True)
    loss_fn = gluon.loss.L2Loss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})

    psnrs = []
    for epoch in range(args.epochs):
        it.reset()
        for batch in it:
            x = batch.data[0].as_in_context(ctx)
            y = batch.label[0].as_in_context(ctx)
            with autograd.record():
                out = net(x)
                loss = loss_fn(out, y)
            loss.backward()
            trainer.step(x.shape[0])
        pred = net(mx.nd.array(lo[:16], ctx=ctx)).asnumpy()
        v = psnr(pred, hi[:16])
        psnrs.append(v)
        logging.info("epoch %d: psnr %.2f dB", epoch, v)
    check_improved("psnr", psnrs, lower_is_better=False)
    print("super-resolution OK: psnr %.2f -> %.2f dB"
          % (psnrs[0], psnrs[-1]))


if __name__ == "__main__":
    main()
