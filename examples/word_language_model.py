#!/usr/bin/env python
"""Word-level language model (reference example/gluon/word_language_model/
train.py workflow): Embedding -> multi-layer LSTM -> tied-or-untied
decoder, truncated BPTT with detached hidden state, gradient clipping,
perplexity per epoch, tokens/sec — the BASELINE.json "Gluon LSTM
tokens/sec" config.

Reads a whitespace-tokenized corpus with --data; without it, a synthetic
Markov-chain corpus is generated so the script runs (and the perplexity
measurably drops) anywhere.
"""
import argparse
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _common import maybe_force_cpu, pick_ctx, check_improved  # noqa: E402
maybe_force_cpu()

import logging
logging.basicConfig(level=logging.INFO)

import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import gluon, autograd


class RNNModel(gluon.Block):
    def __init__(self, vocab_size, embed, hidden, layers, dropout=0.2,
                 tie_weights=False, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.drop = gluon.nn.Dropout(dropout)
            self.encoder = gluon.nn.Embedding(vocab_size, embed)
            self.rnn = gluon.rnn.LSTM(hidden, num_layers=layers,
                                      dropout=dropout, layout="NTC")
            if tie_weights:
                if embed != hidden:
                    raise ValueError(
                        "--tied requires --emsize == --nhid (reference "
                        "word_language_model model.py)")
                self.decoder = gluon.nn.Dense(
                    vocab_size, flatten=False,
                    params=self.encoder.params)
            else:
                self.decoder = gluon.nn.Dense(vocab_size, flatten=False)
        self.hidden = hidden
        self.layers = layers

    def begin_state(self, batch, ctx):
        return self.rnn.begin_state(batch_size=batch, ctx=ctx)

    def forward(self, inputs, hidden):
        emb = self.drop(self.encoder(inputs))
        out, hidden = self.rnn(emb, hidden)
        out = self.drop(out)
        return self.decoder(out), hidden


def synthetic_corpus(vocab=100, n=60000, seed=0):
    """First-order Markov chain: next-token structure an LSTM can learn."""
    rng = np.random.RandomState(seed)
    trans = rng.dirichlet(np.full(vocab, 0.05), size=vocab)
    toks = np.empty(n, np.int32)
    toks[0] = 0
    for i in range(1, n):
        toks[i] = rng.choice(vocab, p=trans[toks[i - 1]])
    return toks, vocab


def load_corpus(path):
    with open(path) as f:
        words = f.read().split()
    vocab = {w: i for i, w in enumerate(sorted(set(words)))}
    return np.array([vocab[w] for w in words], np.int32), len(vocab)


def batchify(toks, batch):
    nb = len(toks) // batch
    return toks[: nb * batch].reshape(batch, nb)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--data", default=None, help="tokenized text file")
    p.add_argument("--emsize", type=int, default=64)
    p.add_argument("--nhid", type=int, default=64)
    p.add_argument("--nlayers", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--bptt", type=int, default=32)
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--lr", type=float, default=2.0)
    p.add_argument("--clip", type=float, default=0.25)
    p.add_argument("--dropout", type=float, default=0.2)
    p.add_argument("--tied", action="store_true")
    p.add_argument("--synthetic-tokens", type=int, default=60000,
                   help="synthetic corpus size when --data is absent")
    p.add_argument("--device", default=None)
    args = p.parse_args()

    ctx = pick_ctx()
    toks, vocab = (load_corpus(args.data) if args.data
                   else synthetic_corpus(n=args.synthetic_tokens))
    data = batchify(toks, args.batch_size)

    model = RNNModel(vocab, args.emsize, args.nhid, args.nlayers,
                     args.dropout, args.tied)
    model.initialize(mx.initializer.Xavier(), ctx=ctx)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(model.collect_params(), "sgd",
                            {"learning_rate": args.lr})

    ppls = []
    nb = (data.shape[1] - 1) // args.bptt
    if nb == 0:
        raise SystemExit(
            "corpus too small: need at least batch_size*(bptt+1) = %d "
            "tokens, got %d" % (args.batch_size * (args.bptt + 1),
                                data.size))
    for epoch in range(args.epochs):
        hidden = model.begin_state(args.batch_size, ctx)
        total, count = 0.0, 0
        tic = time.time()
        for b in range(nb):
            lo = b * args.bptt
            X = mx.nd.array(data[:, lo:lo + args.bptt], ctx=ctx)
            Y = mx.nd.array(data[:, lo + 1:lo + args.bptt + 1], ctx=ctx)
            # truncated BPTT (reference train.py detach)
            hidden = [h.detach() for h in hidden]
            with autograd.record():
                out, hidden = model(X, hidden)
                loss = loss_fn(out, Y)
            loss.backward()
            # reference grad clipping: global rescale by total norm
            grads = [p.grad(ctx) for p in model.collect_params().values()
                     if p.grad_req != "null"]
            # loss is meaned over T already, so grads are per-sample
            # scale: normalize by batch only (reference normalizes by
            # batch*bptt because its loss sums over T)
            gluon.utils.clip_global_norm(
                grads, args.clip * args.batch_size)
            trainer.step(args.batch_size)
            # loss is per-sample, already meaned over the T axis
            # (gluon Loss contract) -> scale back to per-token totals
            total += float(loss.sum().asscalar()) * args.bptt
            count += args.batch_size * args.bptt
        ppl = math.exp(total / count)
        toks_s = count / (time.time() - tic)
        ppls.append(ppl)
        logging.info("epoch %d: ppl %.2f, %.0f tokens/sec",
                     epoch, ppl, toks_s)
    check_improved("perplexity", ppls)
    print("LM training OK: ppl %.2f -> %.2f (vocab %d)"
          % (ppls[0], ppls[-1], vocab))


if __name__ == "__main__":
    main()
