#!/usr/bin/env python
"""Character LSTM with the symbolic mx.rnn package + BucketingModule
(reference example/rnn/bucketing workflow), on a built-in corpus so it
runs anywhere."""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _common import maybe_force_cpu  # noqa: E402
maybe_force_cpu()

import logging
logging.basicConfig(level=logging.INFO)

import numpy as np
import mxnet_tpu as mx

CORPUS = ("the quick brown fox jumps over the lazy dog. "
          "pack my box with five dozen liquor jugs. ") * 40


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--device", default="auto",
                    choices=["auto", "cpu"])
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--num-epochs", type=int, default=3)
    ap.add_argument("--num-hidden", type=int, default=64)
    args = ap.parse_args()

    vocab = {c: i + 1 for i, c in enumerate(sorted(set(CORPUS)))}
    sentences = []
    ids = [vocab[c] for c in CORPUS]
    i = 0
    for j, step in enumerate([24, 12] * (len(ids) // 36 + 1)):
        if i + step + 1 > len(ids):
            break
        sentences.append(ids[i:i + step + 1])
        i += step
    buckets = [13, 25]
    # BucketSentenceIter emits next-token-shifted labels itself
    train = mx.rnn.BucketSentenceIter(sentences, args.batch_size,
                                      buckets=buckets, invalid_label=0)

    n_vocab = len(vocab) + 1

    def sym_gen(seq_len):
        data_s = mx.sym.Variable("data")
        label_s = mx.sym.Variable("softmax_label")
        embed = mx.sym.Embedding(data_s, input_dim=n_vocab, output_dim=32,
                                 name="embed")
        cell = mx.rnn.LSTMCell(args.num_hidden, prefix="lstm_")
        outputs, _ = cell.unroll(seq_len, inputs=embed, layout="NTC",
                                 merge_outputs=True)
        pred = mx.sym.Reshape(outputs, shape=(-1, args.num_hidden))
        pred = mx.sym.FullyConnected(pred, num_hidden=n_vocab, name="pred")
        label = mx.sym.Reshape(label_s, shape=(-1,))
        # label 0 marks bucket padding (invalid_label): excluded from the
        # loss and the metric
        return (mx.sym.SoftmaxOutput(pred, label, name="softmax",
                                     use_ignore=True, ignore_label=0),
                ("data",), ("softmax_label",))

    it = train
    mod = mx.mod.BucketingModule(sym_gen,
                                 default_bucket_key=it.default_bucket_key)
    mod.fit(it, num_epoch=args.num_epochs, optimizer="adam",
            optimizer_params={"learning_rate": 3e-3},
            initializer=mx.initializer.Xavier(),
            eval_metric=mx.metric.Perplexity(ignore_label=0))
    it.reset()
    print("final:", mod.score(it, mx.metric.Perplexity(ignore_label=0)))


if __name__ == "__main__":
    main()
