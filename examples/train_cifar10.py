#!/usr/bin/env python
"""CIFAR-10 ResNet-20/56/110 with the Module API (reference
example/image-classification/train_cifar10.py workflow — BASELINE
config 1). With --data-train/--data-val pointing at cifar10 .rec files
the threaded ImageRecordIter feeds the standard augmentation (pad-4
random crop + mirror, per-channel mean/std); without them a synthetic
learnable stand-in keeps the script runnable anywhere (zero-egress
environments cannot download the real dataset)."""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _common import maybe_force_cpu, check_improved  # noqa: E402
maybe_force_cpu()

import logging
logging.basicConfig(level=logging.INFO)

import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import models

RGB_MEAN = (125.307, 122.961, 113.8575)
RGB_STD = (51.5865, 50.847, 51.255)


def eval_iter(path, args):
    """Deterministic (augmentation-free) scoring iterator."""
    from mxnet_tpu.io import ImageRecordIter
    return ImageRecordIter(
        path, data_shape=(3, 28, 28), batch_size=args.batch_size,
        mean_r=RGB_MEAN[0], mean_g=RGB_MEAN[1], mean_b=RGB_MEAN[2],
        std_r=RGB_STD[0], std_g=RGB_STD[1], std_b=RGB_STD[2])


def rec_iters(args):
    from mxnet_tpu.io import ImageRecordIter
    train = ImageRecordIter(
        args.data_train, data_shape=(3, 28, 28), batch_size=args.batch_size,
        pad=4, rand_crop=True, rand_mirror=True,
        mean_r=RGB_MEAN[0], mean_g=RGB_MEAN[1], mean_b=RGB_MEAN[2],
        std_r=RGB_STD[0], std_g=RGB_STD[1], std_b=RGB_STD[2],
        preprocess_threads=max(os.cpu_count() or 2, 2), shuffle=True)
    val = eval_iter(args.data_val, args) if args.data_val else None
    return train, val


def synthetic_iters(args):
    rng = np.random.RandomState(0)
    n = 2048
    y = rng.randint(0, 10, n).astype(np.float32)
    X = rng.rand(n, 3, 28, 28).astype(np.float32) * 0.1
    for i in range(n):  # class-dependent color patch so the task learns
        c = int(y[i])
        X[i, c % 3, 2 * (c // 3):2 * (c // 3) + 8, 6:22] += 0.9
    cut = n - 512
    return (mx.io.NDArrayIter(X[:cut], y[:cut], args.batch_size,
                              shuffle=True),
            mx.io.NDArrayIter(X[cut:], y[cut:], args.batch_size))


def main():
    p = argparse.ArgumentParser(
        description="train cifar10",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    p.add_argument("--network", default="resnet", choices=["resnet"],
                   help="cifar script trains resnet only (reference "
                        "default); train_imagenet.py has the other nets")
    p.add_argument("--num-layers", type=int, default=20,
                   help="cifar resnet depth: 20, 56 or 110")
    p.add_argument("--data-train", default=None,
                   help="cifar10_train.rec (synthetic stand-in if absent)")
    p.add_argument("--data-val", default=None)
    p.add_argument("--batch-size", type=int, default=128)
    p.add_argument("--num-epochs", type=int, default=10)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--lr-step-epochs", default="200,250")
    p.add_argument("--wd", type=float, default=1e-4)
    p.add_argument("--mom", type=float, default=0.9)
    p.add_argument("--kv-store", default="tpu_sync")
    p.add_argument("--model-prefix", default=None)
    p.add_argument("--device", default=None)
    args = p.parse_args()

    train, val = rec_iters(args) if args.data_train else synthetic_iters(args)

    sym = models.resnet_symbol(num_classes=10, num_layers=args.num_layers,
                               image_shape=(3, 28, 28))
    steps_per_epoch = max(train.num_batches, 1)
    steps = [int(e) * steps_per_epoch
             for e in args.lr_step_epochs.split(",")]
    lr_sched = mx.lr_scheduler.MultiFactorScheduler(step=steps, factor=0.1)

    from _common import pick_ctx
    dev = pick_ctx()
    mod = mx.mod.Module(sym, context=dev)
    accs = []

    def epoch_cb(epoch, symbol, arg_p, aux_p):
        if args.model_prefix:
            mx.model.save_checkpoint(args.model_prefix, epoch + 1, symbol,
                                     arg_p, aux_p)

    def eval_cb(param):
        # fit scores eval_data once per epoch; collect that number
        # instead of paying a second validation pass
        accs.append(dict(param.eval_metric.get_name_value())["accuracy"])

    mod.fit(train, eval_data=val,
            num_epoch=args.num_epochs, eval_metric="acc",
            optimizer="sgd",
            optimizer_params={"learning_rate": args.lr, "momentum": args.mom,
                              "wd": args.wd, "lr_scheduler": lr_sched,
                              "multi_precision": True},
            initializer=mx.initializer.Xavier(factor_type="in",
                                              magnitude=2.0),
            kvstore=args.kv_store, epoch_end_callback=epoch_cb,
            eval_end_callback=eval_cb,
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 50))

    if not accs:
        # no val data: score the TRAIN .rec once, augmentation-free
        clean = eval_iter(args.data_train, args)
        accs.append(dict(mod.score(clean, mx.metric.Accuracy()))
                    ["accuracy"])
        clean.close()
    print("final accuracy: %.4f" % accs[-1])
    if accs[-1] < 0.9:    # saturated runs can't self-compare
        check_improved("accuracy", accs, lower_is_better=False)


if __name__ == "__main__":
    main()
