#!/usr/bin/env python
"""Matrix factorization for recommendation (reference example/sparse/
matrix_factorization/train.py workflow): two SparseEmbedding tables
(users, items) with row-sparse gradients, dot-product scoring, L2 loss —
only the rows a batch touches are ever updated (the sparse-embedding
regime the reference runs over ps-lite; here the lazy-row optimizer
path).

--data takes a MovieLens-format 'user item rating' file; without it a
synthetic low-rank rating matrix is sampled.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _common import maybe_force_cpu, pick_ctx, check_improved  # noqa: E402
maybe_force_cpu()

import logging
logging.basicConfig(level=logging.INFO)

import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import gluon, autograd


class MFBlock(gluon.HybridBlock):
    def __init__(self, num_users, num_items, factor, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.user = gluon.nn.Embedding(num_users, factor,
                                           sparse_grad=True)
            self.item = gluon.nn.Embedding(num_items, factor,
                                           sparse_grad=True)

    def hybrid_forward(self, F, users, items):
        return (self.user(users) * self.item(items)).sum(axis=1)


def synthetic_ratings(num_users=200, num_items=150, rank=6, n=20000,
                      seed=0):
    rng = np.random.RandomState(seed)
    U = rng.randn(num_users, rank) / np.sqrt(rank)
    V = rng.randn(num_items, rank) / np.sqrt(rank)
    u = rng.randint(0, num_users, n)
    i = rng.randint(0, num_items, n)
    r = (U[u] * V[i]).sum(1) + 0.05 * rng.randn(n)
    return u.astype("f4"), i.astype("f4"), r.astype("f4")


def load_ratings(path):
    raw = np.loadtxt(path)
    return (raw[:, 0].astype("f4"), raw[:, 1].astype("f4"),
            raw[:, 2].astype("f4"))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--data", default=None,
                   help="'user item rating' text file")
    p.add_argument("--factor", type=int, default=16)
    p.add_argument("--batch-size", type=int, default=256)
    p.add_argument("--epochs", type=int, default=8)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--optimizer", default="groupadagrad",
                   help="sgd | adagrad | groupadagrad (all lazy-row)")
    p.add_argument("--device", default=None)
    args = p.parse_args()

    ctx = pick_ctx()
    u, i, r = load_ratings(args.data) if args.data else synthetic_ratings()
    nu, ni = int(u.max()) + 1, int(i.max()) + 1
    it = mx.io.NDArrayIter({"user": u, "item": i}, r,
                           batch_size=args.batch_size, shuffle=True,
                           label_name="score")

    net = MFBlock(nu, ni, args.factor)
    net.initialize(mx.initializer.Normal(0.1), ctx=ctx)
    loss_fn = gluon.loss.L2Loss()
    trainer = gluon.Trainer(net.collect_params(), args.optimizer,
                            {"learning_rate": args.lr})

    rmses = []
    for epoch in range(args.epochs):
        it.reset()
        se = count = 0.0
        for batch in it:
            users = batch.data[0].as_in_context(ctx)
            items = batch.data[1].as_in_context(ctx)
            score = batch.label[0].as_in_context(ctx)
            with autograd.record():
                pred = net(users, items)
                loss = loss_fn(pred, score)
            loss.backward()
            # sparse_grad=True: these are RowSparseNDArrays — the
            # optimizer's lazy path touches only the batch's rows
            trainer.step(users.shape[0])
            se += float(((pred - score) ** 2).sum().asscalar())
            count += users.shape[0]
        rmses.append(float(np.sqrt(se / count)))
        logging.info("epoch %d: rmse %.4f", epoch, rmses[-1])
    check_improved("rmse", rmses)
    print("matrix factorization OK: rmse %.4f -> %.4f (%d users, "
          "%d items)" % (rmses[0], rmses[-1], nu, ni))


if __name__ == "__main__":
    main()
