#!/usr/bin/env python
"""Two-stage Faster R-CNN training in miniature (reference example/rcnn
workflow): RPN over a conv backbone with host-side anchor targets (the
reference's AnchorLoader), the Proposal contrib op, a ProposalTarget
**CustomOp** (python operator, exactly how the reference implements it),
ROIPooling, and the two-head loss — cls SoftmaxOutput with ignore labels
+ smooth_l1/MakeLoss bbox regression — trained end to end with
Module.fit on synthetic box images until both losses fall.

This is BASELINE config 4's rcnn half: custom ops + static-shape
handling of a dynamically-sized problem (fixed ROI quota per image).
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _common import maybe_force_cpu, check_improved  # noqa: E402
maybe_force_cpu()

import logging
logging.basicConfig(level=logging.INFO)

import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import operator as mxop

IMG = 128
STRIDE = 16
FEAT = IMG // STRIDE
SCALES = (2, 4, 6)        # anchor sizes 32/64/96 px at stride 16
RATIOS = (1.0,)
A = len(SCALES) * len(RATIOS)
NUM_CLASSES = 3           # background + 2 object classes
ROIS_PER_IMG = 16


def make_anchors():
    """EXACTLY the Proposal op's anchors (vision_ops._make_anchors:
    base box (0,0,bs-1,bs-1), +1 width convention, shift grid k*stride) —
    targets must use the same parameterization the op decodes with."""
    from mxnet_tpu.ops.vision_ops import _make_anchors
    base = _make_anchors(STRIDE, SCALES, RATIOS)    # (A, 4)
    shifts = np.arange(FEAT) * STRIDE
    sx, sy = np.meshgrid(shifts, shifts)
    grid = np.stack([sx, sy, sx, sy], -1).reshape(-1, 1, 4)
    return (grid + base[None]).reshape(-1, 4)       # (FEAT*FEAT*A, 4)


ANCHORS = make_anchors()


def iou(boxes, gt):
    x1 = np.maximum(boxes[:, 0], gt[0])
    y1 = np.maximum(boxes[:, 1], gt[1])
    x2 = np.minimum(boxes[:, 2], gt[2])
    y2 = np.minimum(boxes[:, 3], gt[3])
    inter = np.clip(x2 - x1, 0, None) * np.clip(y2 - y1, 0, None)
    area_b = (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])
    area_g = (gt[2] - gt[0]) * (gt[3] - gt[1])
    return inter / np.maximum(area_b + area_g - inter, 1e-6)


def bbox_transform(anchors, gt):
    """Box -> regression deltas with the reference's +1 width convention
    (rcnn bbox_transform == the Proposal op's decode inverse)."""
    aw = anchors[:, 2] - anchors[:, 0] + 1.0
    ah = anchors[:, 3] - anchors[:, 1] + 1.0
    ax = anchors[:, 0] + (aw - 1) / 2
    ay = anchors[:, 1] + (ah - 1) / 2
    gw, gh = gt[2] - gt[0] + 1.0, gt[3] - gt[1] + 1.0
    gx, gy = gt[0] + (gw - 1) / 2, gt[1] + (gh - 1) / 2
    return np.stack([(gx - ax) / aw, (gy - ay) / ah,
                     np.log(gw / aw), np.log(gh / ah)], -1)


def anchor_targets(gt_box):
    """Host-side RPN targets (the reference AnchorLoader's job)."""
    overlaps = iou(ANCHORS, gt_box)
    label = np.full(len(ANCHORS), -1.0, np.float32)
    label[overlaps < 0.3] = 0.0
    label[overlaps >= 0.5] = 1.0
    label[overlaps.argmax()] = 1.0
    # cap negatives to keep the loss balanced
    neg = np.where(label == 0)[0]
    if len(neg) > 3 * max((label == 1).sum(), 1) + 8:
        drop = np.random.RandomState(0).choice(
            neg, len(neg) - (3 * int((label == 1).sum()) + 8),
            replace=False)
        label[drop] = -1.0
    targets = bbox_transform(ANCHORS, gt_box).astype(np.float32)
    weight = (label == 1).astype(np.float32)[:, None] * np.ones(
        (1, 4), np.float32)
    # layouts the RPN heads emit: label (A*FEAT*FEAT,), bbox (4A, F, F)
    lab = label.reshape(FEAT * FEAT, A).T.reshape(-1)
    tgt = targets.reshape(FEAT, FEAT, A * 4).transpose(2, 0, 1)
    wgt = weight.reshape(FEAT, FEAT, A * 4).transpose(2, 0, 1)
    return lab, tgt, wgt


@mxop.register("proposal_target")
class ProposalTargetProp(mxop.CustomOpProp):
    """Sample a fixed ROI quota per image and label it against the gt box
    (reference example/rcnn proposal_target.py — a python CustomOp)."""

    def __init__(self):
        super().__init__(need_top_grad=False)

    def list_arguments(self):
        return ["rois", "gt_boxes"]

    def list_outputs(self):
        return ["rois_out", "label", "bbox_target", "bbox_weight"]

    def infer_shape(self, in_shape):
        n_img = in_shape[1][0]
        n = n_img * ROIS_PER_IMG
        return in_shape, [(n, 5), (n,), (n, 4 * NUM_CLASSES),
                          (n, 4 * NUM_CLASSES)], []

    def create_operator(self, ctx, shapes, dtypes):
        return ProposalTarget()


class ProposalTarget(mxop.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        rois = in_data[0].asnumpy()          # (R, 5) [batch, x1..y2]
        gts = in_data[1].asnumpy()           # (N, 5) [x1..y2, cls]
        out_r, out_l, out_t, out_w = [], [], [], []
        for b in range(len(gts)):
            gt = gts[b]
            mine = rois[rois[:, 0] == b][:, 1:]
            # drop the Proposal op's [-1,-1,-1,-1] NMS padding rows — they
            # would otherwise fill the background quota with zero-feature
            # samples (reference pads with repeated VALID proposals)
            mine = mine[mine[:, 2] > mine[:, 0]]
            if len(mine) == 0:
                mine = ANCHORS[:1]
            # gt box always joins the pool (reference does the same)
            pool = np.vstack([mine, gt[None, :4]])
            ov = iou(pool, gt[:4])
            order = np.argsort(-ov)
            fg = order[ov[order] >= 0.5][: ROIS_PER_IMG // 4]
            bg = order[ov[order] < 0.5][: ROIS_PER_IMG - len(fg)]
            keep = np.concatenate([fg, bg])
            if len(keep) < ROIS_PER_IMG:    # pad by repeating
                keep = np.resize(keep, ROIS_PER_IMG)
            sel = pool[keep]
            lab = np.zeros(ROIS_PER_IMG, np.float32)
            lab[: len(fg)] = gt[4] + 1      # class id (0 = background)
            tgt = np.zeros((ROIS_PER_IMG, 4 * NUM_CLASSES), np.float32)
            wgt = np.zeros_like(tgt)
            deltas = bbox_transform(sel[: len(fg)], gt[:4]) \
                if len(fg) else np.zeros((0, 4))
            for j in range(len(fg)):
                c = int(lab[j])
                tgt[j, 4 * c:4 * c + 4] = deltas[j]
                wgt[j, 4 * c:4 * c + 4] = 1.0
            out_r.append(np.hstack([np.full((ROIS_PER_IMG, 1), b,
                                            np.float32), sel]))
            out_l.append(lab)
            out_t.append(tgt)
            out_w.append(wgt)
        self.assign(out_data[0], req[0], np.vstack(out_r))
        self.assign(out_data[1], req[1], np.concatenate(out_l))
        self.assign(out_data[2], req[2], np.vstack(out_t))
        self.assign(out_data[3], req[3], np.vstack(out_w))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        for g in in_grad:                     # sampling has no gradient
            self.assign(g, "write", 0 * g)


def rcnn_symbol():
    data = mx.sym.Variable("data")
    im_info = mx.sym.Variable("im_info")
    gt_boxes = mx.sym.Variable("gt_boxes")
    rpn_label = mx.sym.Variable("rpn_label")
    rpn_bbox_target = mx.sym.Variable("rpn_bbox_target")
    rpn_bbox_weight = mx.sym.Variable("rpn_bbox_weight")

    def conv_block(x, nf, name, stride=1):
        x = mx.sym.Convolution(x, kernel=(3, 3), stride=(stride, stride),
                               pad=(1, 1), num_filter=nf, name=name)
        return mx.sym.Activation(x, act_type="relu")

    x = conv_block(data, 16, "c1", 2)
    x = conv_block(x, 32, "c2", 2)
    x = conv_block(x, 32, "c3", 2)
    feat = conv_block(x, 64, "c4", 2)          # stride 16

    rpn = conv_block(feat, 64, "rpn_conv")
    rpn_cls = mx.sym.Convolution(rpn, kernel=(1, 1), num_filter=2 * A,
                                 name="rpn_cls_score")
    rpn_bbox = mx.sym.Convolution(rpn, kernel=(1, 1), num_filter=4 * A,
                                  name="rpn_bbox_pred")

    # RPN losses (reference symbol_vgg.py get_vgg_rpn semantics)
    rpn_cls_r = mx.sym.reshape(rpn_cls, shape=(0, 2, -1))
    rpn_cls_prob = mx.sym.SoftmaxOutput(
        rpn_cls_r, rpn_label, multi_output=True, use_ignore=True,
        ignore_label=-1, normalization="valid", name="rpn_cls_prob")
    rpn_bbox_loss = mx.sym.MakeLoss(
        mx.sym.smooth_l1(rpn_bbox_weight * (rpn_bbox - rpn_bbox_target),
                         scalar=3.0),
        grad_scale=1.0 / (FEAT * FEAT), name="rpn_bbox_loss")

    # proposals (no grad through the sampling) -> fixed ROI quota
    score_shape = mx.sym.reshape(rpn_cls, shape=(0, 2, A, FEAT, FEAT))
    probs = mx.sym.softmax(score_shape, axis=1)
    probs = mx.sym.reshape(probs, shape=(0, 2 * A, FEAT, FEAT))
    rois = mx.sym.contrib.Proposal(
        mx.sym.BlockGrad(probs), mx.sym.BlockGrad(rpn_bbox), im_info,
        feature_stride=STRIDE, scales=SCALES, ratios=RATIOS,
        rpn_pre_nms_top_n=64, rpn_post_nms_top_n=ROIS_PER_IMG,
        threshold=0.7, rpn_min_size=8, name="rois")
    target = mx.sym.Custom(rois=rois, gt_boxes=gt_boxes,
                           op_type="proposal_target", name="pt")
    rois_s, label, bbox_target, bbox_weight = (
        target[0], target[1], target[2], target[3])

    pooled = mx.sym.ROIPooling(feat, rois_s, pooled_size=(4, 4),
                               spatial_scale=1.0 / STRIDE, name="roi_pool")
    h = mx.sym.Activation(
        mx.sym.FullyConnected(pooled, num_hidden=128, name="fc6"),
        act_type="relu")
    cls_prob = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(h, num_hidden=NUM_CLASSES, name="cls_score"),
        mx.sym.BlockGrad(label), normalization="valid", name="cls_prob")
    bbox_loss = mx.sym.MakeLoss(
        mx.sym.smooth_l1(
            bbox_weight * (mx.sym.FullyConnected(
                h, num_hidden=4 * NUM_CLASSES, name="bbox_pred")
                - bbox_target), scalar=1.0),
        grad_scale=1.0 / ROIS_PER_IMG, name="bbox_loss")
    return mx.sym.Group([rpn_cls_prob, rpn_bbox_loss, cls_prob, bbox_loss,
                         mx.sym.BlockGrad(label)])


class RCNNIter(mx.io.DataIter):
    """Synthetic detection batches + host-side RPN anchor targets."""

    def __init__(self, n=64, batch_size=2, seed=0):
        super().__init__(batch_size)
        rng = np.random.RandomState(seed)
        self.data, self.gt = [], []
        for _ in range(n):
            img = rng.rand(3, IMG, IMG).astype(np.float32) * 0.1
            cls = rng.randint(0, NUM_CLASSES - 1)
            size = rng.randint(36, 80)
            x1 = rng.randint(0, IMG - size)
            y1 = rng.randint(0, IMG - size)
            img[cls, y1:y1 + size, x1:x1 + size] += 0.8
            self.data.append(img)
            self.gt.append(np.array([x1, y1, x1 + size, y1 + size, cls],
                                    np.float32))
        self.n = n
        self.reset()

    @property
    def provide_data(self):
        return [("data", (self.batch_size, 3, IMG, IMG)),
                ("im_info", (self.batch_size, 3)),
                ("gt_boxes", (self.batch_size, 5))]

    @property
    def provide_label(self):
        return [("rpn_label", (self.batch_size, A * FEAT * FEAT)),
                ("rpn_bbox_target", (self.batch_size, 4 * A, FEAT, FEAT)),
                ("rpn_bbox_weight", (self.batch_size, 4 * A, FEAT, FEAT))]

    def reset(self):
        self.cursor = -self.batch_size

    def next(self):
        from mxnet_tpu.io import DataBatch
        self.cursor += self.batch_size
        if self.cursor + self.batch_size > self.n:
            raise StopIteration
        sl = slice(self.cursor, self.cursor + self.batch_size)
        imgs = np.stack(self.data[sl])
        gts = np.stack(self.gt[sl])
        labs, tgts, wgts = zip(*(anchor_targets(g[:4]) for g in self.gt[sl]))
        info = np.tile([IMG, IMG, 1.0], (self.batch_size, 1)) \
            .astype(np.float32)
        return DataBatch(
            data=[mx.nd.array(imgs), mx.nd.array(info), mx.nd.array(gts)],
            label=[mx.nd.array(np.stack(labs)), mx.nd.array(np.stack(tgts)),
                   mx.nd.array(np.stack(wgts))], pad=0)


class RCNNMetric(mx.metric.EvalMetric):
    """rpn_cls NLL + head cls NLL (reference rcnn metric set)."""

    def __init__(self):
        super().__init__("rcnn_loss")

    def update(self, labels, preds):
        rpn_prob = preds[0].asnumpy()          # (B, 2, A*F*F)
        rpn_lab = labels[0].asnumpy()
        m = rpn_lab >= 0
        idx = rpn_lab.clip(0).astype(int)
        p = np.take_along_axis(rpn_prob, idx[:, None, :], 1)[:, 0][m]
        rpn_nll = -np.log(np.maximum(p, 1e-9)).sum()
        cls_prob = preds[2].asnumpy()          # (B*R, C)
        lab = preds[4].asnumpy().astype(int).ravel()
        pc = cls_prob[np.arange(len(lab)), lab]
        cls_nll = -np.log(np.maximum(pc, 1e-9)).sum()
        self.sum_metric += rpn_nll + cls_nll
        self.num_inst += m.sum() + len(lab)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=5)
    p.add_argument("--batch-size", type=int, default=2)
    p.add_argument("--lr", type=float, default=0.01)
    p.add_argument("--kv-store", default="local")
    p.add_argument("--seed", type=int, default=7)
    p.add_argument("--device", default=None)
    args = p.parse_args()

    mx.random.seed(args.seed)
    np.random.seed(args.seed)
    it = RCNNIter(batch_size=args.batch_size, seed=args.seed)
    sym = rcnn_symbol()
    mod = mx.mod.Module(sym,
                        data_names=("data", "im_info", "gt_boxes"),
                        label_names=("rpn_label", "rpn_bbox_target",
                                     "rpn_bbox_weight"))
    metric = RCNNMetric()
    losses = []

    def epoch_cb(epoch, s, a, b):
        losses.append(metric.get()[1])

    mod.fit(it, num_epoch=args.epochs, optimizer="sgd",
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9,
                              "wd": 5e-4},
            initializer=mx.initializer.Xavier(),
            kvstore=args.kv_store, eval_metric=metric,
            epoch_end_callback=epoch_cb)
    for e, v in enumerate(losses):
        logging.info("epoch %d: loss %.3f", e, v)
    check_improved("rcnn loss", losses)
    print("Faster R-CNN training OK: loss %.3f -> %.3f"
          % (losses[0], losses[-1]))


if __name__ == "__main__":
    main()
