#!/usr/bin/env python
"""ImageNet-scale classification training CLI (reference
example/image-classification/train_imagenet.py workflow): RecordIO data
via the threaded ImageRecordIter, model-zoo symbols, Module.fit with the
fused tpu_sync step, multi-precision bf16, checkpointing, and the
reference's --benchmark 1 mode (one synthetic device-resident batch,
throughput printed).

    python train_imagenet.py --benchmark 1 --network resnet --num-layers 50
    python train_imagenet.py --data-train train.rec --network inception-v3
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _common import maybe_force_cpu, pick_ctx  # noqa: E402
maybe_force_cpu()

import logging
logging.basicConfig(level=logging.INFO)

import numpy as np
import mxnet_tpu as mx


def build_symbol(args):
    from mxnet_tpu import models
    if args.network == "resnet":
        return models.resnet_symbol(num_classes=args.num_classes,
                                    num_layers=args.num_layers,
                                    image_shape=args.image_shape)
    if args.network == "inception-v3":
        return models.inception_v3_symbol(num_classes=args.num_classes)
    if args.network == "alexnet":
        return models.alexnet_symbol(num_classes=args.num_classes)
    raise SystemExit("unknown --network %r" % args.network)


class _OneBatchIter:
    """--benchmark 1: one device-resident synthetic batch, repeated."""

    def __init__(self, batch, steps, provide_data, provide_label):
        self._batch, self._steps = batch, steps
        self.provide_data, self.provide_label = provide_data, provide_label
        self.batch_size = provide_data[0].shape[0]
        self._i = 0

    def __iter__(self):
        return self

    def __next__(self):
        if self._i >= self._steps:
            raise StopIteration
        self._i += 1
        return self._batch

    def reset(self):
        self._i = 0


def get_data(args, ctx):
    shp = tuple(int(x) for x in args.image_shape.split(","))
    if args.benchmark:
        from mxnet_tpu.io import DataBatch, DataDesc
        rng = np.random.RandomState(0)
        data = mx.nd.array(rng.randn(args.batch_size, *shp)
                           .astype(np.float32), ctx=ctx)
        label = mx.nd.array(rng.randint(0, args.num_classes,
                                        (args.batch_size,))
                            .astype(np.float32), ctx=ctx)
        it = _OneBatchIter(
            DataBatch(data=[data], label=[label]), args.benchmark_steps,
            [DataDesc("data", (args.batch_size,) + shp)],
            [DataDesc("softmax_label", (args.batch_size,))])
        return it, None
    if not args.data_train:
        raise SystemExit("--data-train is required unless --benchmark 1")
    from mxnet_tpu.io import ImageRecordIter
    train = ImageRecordIter(
        args.data_train, data_shape=shp, batch_size=args.batch_size,
        rand_crop=True, rand_mirror=True,
        preprocess_threads=args.data_nthreads, shuffle=True, ctx=ctx)
    val = None
    if args.data_val:
        val = ImageRecordIter(
            args.data_val, data_shape=shp, batch_size=args.batch_size,
            preprocess_threads=args.data_nthreads, ctx=ctx)
    return train, val


def main():
    p = argparse.ArgumentParser(
        description="train on imagenet-shaped data",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    p.add_argument("--network", default="resnet")
    p.add_argument("--num-layers", type=int, default=50)
    p.add_argument("--num-classes", type=int, default=1000)
    p.add_argument("--image-shape", default="3,224,224")
    p.add_argument("--data-train", default=None)
    p.add_argument("--data-val", default=None)
    p.add_argument("--data-nthreads", type=int, default=4)
    p.add_argument("--batch-size", type=int, default=128)
    p.add_argument("--num-epochs", type=int, default=1)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--mom", type=float, default=0.9)
    p.add_argument("--wd", type=float, default=1e-4)
    p.add_argument("--lr-step-epochs", default=None,
                   help="e.g. 30,60 (FactorScheduler 0.1)")
    p.add_argument("--kv-store", default="tpu_sync")
    p.add_argument("--multi-precision", type=int, default=1,
                   help="bf16 compute over f32 master weights")
    p.add_argument("--benchmark", type=int, default=0)
    p.add_argument("--benchmark-steps", type=int, default=30)
    p.add_argument("--model-prefix", default=None)
    p.add_argument("--load-epoch", type=int, default=None)
    p.add_argument("--disp-batches", type=int, default=20)
    p.add_argument("--device", default=None)
    p.add_argument("--steps-per-dispatch", type=int, default=1,
                   help="K>1: run K fused steps per XLA dispatch "
                        "(lax.scan over stacked batches); amortises "
                        "host dispatch latency")
    args = p.parse_args()

    ctx = pick_ctx()
    train, val = get_data(args, ctx)
    sym = build_symbol(args)

    opt_params = {"learning_rate": args.lr, "momentum": args.mom,
                  "wd": args.wd, "multi_precision": bool(args.multi_precision)}
    if args.lr_step_epochs and not args.benchmark:
        steps_per_epoch = max(1, getattr(train, "num_batches", 1000))
        opt_params["lr_scheduler"] = mx.lr_scheduler.MultiFactorScheduler(
            [int(e) * steps_per_epoch
             for e in args.lr_step_epochs.split(",")], factor=0.1)

    mod = mx.mod.Module(sym, context=ctx)
    arg_p = aux_p = None
    if args.model_prefix and args.load_epoch is not None:
        _, arg_p, aux_p = mx.model.load_checkpoint(args.model_prefix,
                                                   args.load_epoch)

    cbs = [mx.callback.Speedometer(args.batch_size, args.disp_batches)]
    ep_cbs = []
    if args.model_prefix:
        ep_cbs.append(mx.callback.do_checkpoint(args.model_prefix))

    times = []
    if args.benchmark:
        def bench_cb(epoch, symbol, a, b):
            import jax as _j
            _j.device_get(mod._exec.arg_dict[mod._param_names[0]]._data)
            times.append(time.perf_counter())
        ep_cbs.append(bench_cb)

    mod.fit(train, eval_data=val,
            num_epoch=3 if args.benchmark else args.num_epochs,
            eval_metric=None if args.benchmark else "acc",
            kvstore=args.kv_store, optimizer="sgd",
            optimizer_params=opt_params,
            initializer=mx.initializer.Xavier(rnd_type="gaussian",
                                              factor_type="in",
                                              magnitude=2),
            arg_params=arg_p, aux_params=aux_p,
            begin_epoch=args.load_epoch or 0,
            batch_end_callback=None if args.benchmark else cbs,
            epoch_end_callback=ep_cbs,
            steps_per_dispatch=args.steps_per_dispatch)

    if args.benchmark and len(times) >= 2:
        import jax
        dt = times[-1] - times[0]
        n = args.benchmark_steps * (len(times) - 1)
        print("benchmark: %.2f img/s (batch %d, %s)"
              % (args.batch_size * n / dt, args.batch_size,
                 jax.devices()[0].device_kind))


if __name__ == "__main__":
    main()
