"""Reshard a checkpoint or a serving artifact to a new world/mesh.

Checkpoint -> checkpoint (elastic resume: a run killed at world N
resumes at world N-k or N+k, bitwise):

    python tools/reshard.py --checkpoint /ckpt/run1 --world 3 \
        [--dst /ckpt/run1-w3] [--step 1200]

Artifact -> artifact (re-target a generate ``.mxtpu`` export to a
different inference mesh without touching the checkpoint; served
tokens stay bitwise-equal — sampling folds (seed, position), never
cache geometry):

    python tools/reshard.py --artifact model.mxtpu --dst model-8s.mxtpu \
        --max-slots 8 --num-pages 65 [--page-size P] \
        [--max-pages-per-slot K]

Both paths go through the layout manifest
(mxnet_tpu/parallel/layout.py): gather every parameter from the old
layout, re-slice per the new one, stamp the new manifest + fingerprint.
Prints a one-line JSON report; exit 0 on success.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    p = argparse.ArgumentParser()
    g = p.add_mutually_exclusive_group(required=True)
    g.add_argument("--checkpoint", metavar="ROOT",
                   help="CheckpointManager root (rank_* subdirs) to "
                        "reshard to --world ranks")
    g.add_argument("--artifact", metavar="SRC.mxtpu",
                   help="generate artifact to re-target to a new "
                        "inference mesh (needs bundled weights)")
    p.add_argument("--world", type=int, default=None,
                   help="target world size (checkpoint mode)")
    p.add_argument("--dst", default=None,
                   help="destination root/path (checkpoint default: "
                        "<ROOT>-w<WORLD>; required for --artifact)")
    p.add_argument("--step", type=int, default=None,
                   help="checkpoint step to reshard (default: newest "
                        "step committed by every rank)")
    p.add_argument("--max-slots", type=int, default=None,
                   help="new decode slot count (artifact mode)")
    p.add_argument("--num-pages", type=int, default=None,
                   help="new KV page-pool size (artifact mode)")
    p.add_argument("--max-pages-per-slot", type=int, default=None,
                   help="new per-slot page cap (artifact mode; "
                        "page_size * max_pages_per_slot may shrink "
                        "max_context but never grow it)")
    p.add_argument("--page-size", type=int, default=None,
                   help="new tokens-per-page (artifact mode)")
    p.add_argument("--platform", default=None, choices=[None, "cpu"],
                   help="force the re-export's compile platform "
                        "(artifact mode)")
    args = p.parse_args(argv)

    if args.checkpoint:
        if not args.world or args.world < 1:
            p.error("--checkpoint needs --world N (>= 1)")
        from mxnet_tpu.checkpoint import reshard_checkpoint
        report = reshard_checkpoint(args.checkpoint, args.world,
                                    dst_root=args.dst, step=args.step)
    else:
        if not args.dst:
            p.error("--artifact needs --dst PATH")
        if all(v is None for v in (args.max_slots, args.num_pages,
                                   args.max_pages_per_slot,
                                   args.page_size)):
            p.error("--artifact needs at least one of --max-slots / "
                    "--num-pages / --max-pages-per-slot / --page-size")
        from mxnet_tpu.serving import reshard_artifact
        report = reshard_artifact(
            args.artifact, args.dst, max_slots=args.max_slots,
            num_pages=args.num_pages,
            max_pages_per_slot=args.max_pages_per_slot,
            page_size=args.page_size, platforms=args.platform)
    print(json.dumps(report))


if __name__ == "__main__":
    main()
