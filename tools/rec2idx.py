#!/usr/bin/env python
"""Create a .idx index file for an existing .rec RecordIO file.

Parity: /root/reference/tools/rec2idx.py (IndexCreator over the C
MXRecordIOReaderTell API). Ours walks the record with
:class:`mxnet_tpu.recordio.MXRecordIO` — `tell()` is native to the reader —
and writes the same ``key\\tbyte-offset`` text format MXIndexedRecordIO
consumes.

Usage: python tools/rec2idx.py data/test.rec data/test.idx
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mxnet_tpu import recordio  # noqa: E402


class IndexCreator(recordio.MXRecordIO):
    """Reads a ``.rec`` file and writes the random-access index."""

    def __init__(self, uri, idx_path, key_type=int):
        self.key_type = key_type
        self.fidx = None
        self.idx_path = idx_path
        super().__init__(uri, "r")

    def open(self):
        super().open()
        self.fidx = open(self.idx_path, "w")

    def close(self):
        if self.fidx is not None and not self.fidx.closed:
            self.fidx.close()
        super().close()

    def create_index(self, log_every=1000):
        self.reset()
        counter = 0
        t0 = time.time()
        while True:
            if counter and counter % log_every == 0:
                print("time: %.2fs  count: %d" % (time.time() - t0, counter))
            pos = self.tell()
            if self.read() is None:
                break
            self.fidx.write("%s\t%d\n" % (self.key_type(counter), pos))
            counter += 1
        return counter


def main():
    p = argparse.ArgumentParser(
        formatter_class=argparse.ArgumentDefaultsHelpFormatter,
        description="Create an index file from a .rec file")
    p.add_argument("record", help="path to .rec file")
    p.add_argument("index", help="path to index file (created/overwritten)")
    args = p.parse_args()

    creator = IndexCreator(os.path.abspath(args.record),
                           os.path.abspath(args.index))
    n = creator.create_index()
    creator.close()
    print("indexed %d records" % n)


if __name__ == "__main__":
    main()
