"""int8-vs-bf16 MXU throughput microbenchmark (VERDICT r4 item 5).

The reference's int8 deployment story rests on int8 inference being
faster than the float path (reference contrib/quantization.py:84-205,
src/operator/quantization/quantize_graph_pass.cc). Our quantized ops
lower to `lax.dot_general`/`conv_general_dilated` with int8 inputs and
`preferred_element_type=int32` (ops/quantization.py) — this benchmark
proves on hardware that the integer path actually engages the MXU
rather than silently upcasting: it times the SAME shapes in bf16 and
int8 and reports achieved TOP/s for both.

Shapes: the ResNet-50 hot convs plus square FC matmuls. Each case
prints one line; the summary prints int8/bf16 throughput ratios.

    python tools/microbench_int8.py --iters 50
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


MATMUL_SHAPES = [  # (M, K, N)
    (1024, 1024, 1024),
    (4096, 4096, 4096),
    (8192, 8192, 8192),
    (128, 2048, 1000),     # ResNet-50 classifier at batch 128
]

# (N, C, H, W, O, kh, kw, stride) — ResNet-50 hot convs at batch 128
CONV_SHAPES = [
    (128, 256, 56, 56, 64, 1, 1, 1),
    (128, 128, 28, 28, 128, 3, 3, 1),
    (128, 256, 14, 14, 256, 3, 3, 1),
    (128, 512, 7, 7, 512, 3, 3, 1),
]


def _time_fn(fn, *args, iters=50):
    import jax
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def bench_matmuls(iters):
    import jax
    import jax.numpy as jnp
    from jax import lax

    rows = []
    for m, k, n in MATMUL_SHAPES:
        rng = np.random.RandomState(0)
        a_f = jnp.asarray(rng.randn(m, k), jnp.bfloat16)
        b_f = jnp.asarray(rng.randn(k, n), jnp.bfloat16)
        a_i = jnp.asarray(rng.randint(-127, 128, (m, k)), jnp.int8)
        b_i = jnp.asarray(rng.randint(-127, 128, (k, n)), jnp.int8)

        f_bf16 = jax.jit(lambda a, b: lax.dot_general(
            a, b, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32))
        f_int8 = jax.jit(lambda a, b: lax.dot_general(
            a, b, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32))

        ops = 2.0 * m * k * n
        t_f = _time_fn(f_bf16, a_f, b_f, iters=iters)
        t_i = _time_fn(f_int8, a_i, b_i, iters=iters)
        rows.append(("matmul %dx%dx%d" % (m, k, n),
                     ops / t_f / 1e12, ops / t_i / 1e12))
        print("matmul %5dx%5dx%5d  bf16 %7.1f TOP/s  int8 %7.1f TOP/s  "
              "ratio %.2fx" % (m, k, n, ops / t_f / 1e12, ops / t_i / 1e12,
                               t_f / t_i), flush=True)
    return rows


def bench_convs(iters):
    import jax
    import jax.numpy as jnp
    from jax import lax

    rows = []
    for (n, c, h, w, o, kh, kw, s) in CONV_SHAPES:
        rng = np.random.RandomState(0)
        pad = kh // 2
        x_f = jnp.asarray(rng.randn(n, c, h, w), jnp.bfloat16)
        k_f = jnp.asarray(rng.randn(o, c, kh, kw), jnp.bfloat16)
        x_i = jnp.asarray(rng.randint(-127, 128, (n, c, h, w)), jnp.int8)
        k_i = jnp.asarray(rng.randint(-127, 128, (o, c, kh, kw)), jnp.int8)
        dn = lax.conv_dimension_numbers(x_f.shape, k_f.shape,
                                        ("NCHW", "OIHW", "NCHW"))

        def conv(x, k, ptype):
            return lax.conv_general_dilated(
                x, k, window_strides=(s, s), padding=[(pad, pad)] * 2,
                dimension_numbers=dn, preferred_element_type=ptype)

        f_bf16 = jax.jit(lambda x, k: conv(x, k, jnp.float32))
        f_int8 = jax.jit(lambda x, k: conv(x, k, jnp.int32))

        oh, ow = h // s, w // s
        ops = 2.0 * n * o * oh * ow * c * kh * kw
        t_f = _time_fn(f_bf16, x_f, k_f, iters=iters)
        t_i = _time_fn(f_int8, x_i, k_i, iters=iters)
        rows.append(("conv %dx%dx%dx%d k%d" % (n, c, h, w, kh),
                     ops / t_f / 1e12, ops / t_i / 1e12))
        print("conv  n%d c%4d %3dx%3d o%4d k%d  bf16 %7.1f TOP/s  int8 "
              "%7.1f TOP/s  ratio %.2fx" % (n, c, h, w, o, kh,
                                            ops / t_f / 1e12,
                                            ops / t_i / 1e12, t_f / t_i),
              flush=True)
    return rows


def bench_quantized_fc(iters):
    """End-to-end registered op: quantize -> quantized FC -> dequantize,
    vs the bf16 Dense it replaces — the serving-path comparison."""
    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx  # noqa: F401  (registers ops)
    from mxnet_tpu.ops import quantization as q

    m, k, n = 128, 2048, 1000
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(m, k), jnp.float32)
    wgt = jnp.asarray(rng.randn(n, k), jnp.float32)

    qw, w_lo, w_hi = q.quantize_v2(wgt, out_type="int8")

    @jax.jit
    def int8_path(x, qw, w_lo, w_hi):
        qx, x_lo, x_hi = q.quantize_v2(x, out_type="int8")
        acc, o_lo, o_hi = q.quantized_fully_connected(
            qx, qw, None, x_lo, x_hi, w_lo, w_hi, None, None,
            num_hidden=n, no_bias=True)
        return q.dequantize(acc, o_lo, o_hi)

    @jax.jit
    def bf16_path(x, w):
        return (x.astype(jnp.bfloat16) @ w.astype(jnp.bfloat16).T
                ).astype(jnp.float32)

    t_i = _time_fn(int8_path, x, qw, w_lo, w_hi, iters=iters)
    t_f = _time_fn(bf16_path, x, wgt, iters=iters)
    print("quantized FC end-to-end %dx%dx%d  bf16 %.3f ms  int8(+q/dq) "
          "%.3f ms  ratio %.2fx" % (m, k, n, t_f * 1e3, t_i * 1e3,
                                    t_f / t_i), flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=50)
    args = ap.parse_args()
    import jax
    dev = jax.devices()[0]
    print("device: %s (%s)" % (dev.device_kind, dev.platform), flush=True)
    m = bench_matmuls(args.iters)
    c = bench_convs(args.iters)
    bench_quantized_fc(args.iters)
    ratios = [i / f for (_, f, i) in m + c if f > 0]
    print("int8/bf16 throughput ratio: min %.2f median %.2f max %.2f"
          % (min(ratios), sorted(ratios)[len(ratios) // 2], max(ratios)))


if __name__ == "__main__":
    main()
