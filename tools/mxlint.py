#!/usr/bin/env python
"""mxlint CLI: TPU-discipline static analysis over Python source.

    python tools/mxlint.py                      # lint mxnet_tpu tools examples
    python tools/mxlint.py mxnet_tpu/serve      # lint a subtree
    python tools/mxlint.py --changed            # only git-diffed files
    python tools/mxlint.py --json               # machine-readable output
    python tools/mxlint.py --rule MXL401        # one rule family
    python tools/mxlint.py --concurrency        # Layer-3 only (MXL6xx)
    python tools/mxlint.py --baseline-update    # prune paid-off debt
    python tools/mxlint.py --list-rules         # rule catalog

Exit codes: 0 = clean (or all findings baselined), 1 = new violations,
2 = internal/usage error. The committed baseline (tools/mxlint_baseline
.json) is a one-way ratchet: --baseline-update shrinks it, and refuses
to grow it without --allow-growth. See docs/lint.md for the rule catalog.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from mxnet_tpu.analysis import baseline as baseline_mod   # noqa: E402
from mxnet_tpu.analysis import runner                     # noqa: E402

DEFAULT_PATHS = ["mxnet_tpu", "tools", "examples"]
DEFAULT_BASELINE = os.path.join("tools", "mxlint_baseline.json")

# the Layer-3 scope: concurrency races + control-plane invariants
# (MXL001 rides along — an unparseable file can't be vouched for)
CONCURRENCY_SCOPE = frozenset([
    "MXL001", "MXL601", "MXL602", "MXL603", "MXL604", "MXL605", "MXL606",
])


def _parse_args(argv):
    ap = argparse.ArgumentParser(
        prog="mxlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: %s)"
                    % " ".join(DEFAULT_PATHS))
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit diagnostics as one JSON object")
    ap.add_argument("--rule", action="append", default=None,
                    help="only run this rule id (repeatable)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file (default %(default)s)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline (report all findings as new)")
    ap.add_argument("--baseline-update", action="store_true",
                    help="rewrite the baseline from current findings "
                         "(shrink-only unless --allow-growth)")
    ap.add_argument("--allow-growth", action="store_true",
                    help="let --baseline-update ADD entries")
    ap.add_argument("--concurrency", action="store_true",
                    help="run only the Layer-3 concurrency/control-plane "
                         "rules (MXL601-606)")
    ap.add_argument("--changed", action="store_true",
                    help="lint only files in `git diff --name-only HEAD`")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    return ap.parse_args(argv)


def _list_rules():
    rules = runner.all_rules()
    for rid in sorted(rules):
        r = rules[rid]
        print("%s  %-26s %-7s %s" % (rid, r.name, r.severity, r.hint))
    return 0


def main(argv=None):
    args = _parse_args(argv if argv is not None else sys.argv[1:])
    if args.list_rules:
        return _list_rules()

    enabled = None
    if args.rule:
        known = runner.all_rules()
        bad = [r for r in args.rule if r not in known]
        if bad:
            print("mxlint: unknown rule id(s): %s (see --list-rules)"
                  % ", ".join(bad), file=sys.stderr)
            return 2
        enabled = frozenset(args.rule)
    if args.concurrency:
        enabled = (enabled & CONCURRENCY_SCOPE if enabled is not None
                   else CONCURRENCY_SCOPE)

    if args.changed:
        paths = runner.changed_files(root=_REPO)
        if paths is None:
            print("mxlint: git unavailable; falling back to full lint",
                  file=sys.stderr)
            paths = args.paths or DEFAULT_PATHS
        elif not paths:
            if args.as_json:
                print(json.dumps({"diagnostics": [], "new": 0,
                                  "baselined": 0, "stale": []}))
            else:
                print("mxlint: no changed .py files")
            return 0
    else:
        paths = args.paths or DEFAULT_PATHS

    baseline_path = None if args.no_baseline else args.baseline

    try:
        result = runner.run(paths, baseline_path=baseline_path,
                            enabled=enabled, root=_REPO)
    except Exception as e:   # internal error, distinct exit code
        print("mxlint: internal error: %s: %s"
              % (type(e).__name__, e), file=sys.stderr)
        return 2

    if args.baseline_update:
        if args.rule or args.changed or args.paths or args.concurrency:
            print("mxlint: --baseline-update requires a full default-"
                  "scope run (no --rule/--concurrency/--changed/path "
                  "args): a partial run would prune entries it never "
                  "scanned", file=sys.stderr)
            return 2
        try:
            entries = baseline_mod.update(args.baseline, result.diags,
                                          allow_growth=args.allow_growth)
        except baseline_mod.BaselineGrowthError as e:
            print("mxlint: %s" % e, file=sys.stderr)
            return 1
        print("mxlint: baseline %s now has %d entries"
              % (args.baseline, len(entries)))
        return 0

    # a filtered run (--rule/--changed/explicit subset) cannot see every
    # diagnostic, so absent baseline keys are not evidence of paid debt
    full_scope = not (args.rule or args.changed or args.paths
                      or args.concurrency)
    stale = result.stale if full_scope else []

    if args.as_json:
        print(json.dumps({
            "diagnostics": [d.to_dict() for d in result.new],
            "baselined": len(result.baselined),
            "new": len(result.new),
            "stale": stale,
        }, indent=2))
    else:
        for d in result.new:
            print(d.format())
        if stale:
            print("mxlint: %d baseline entr%s no longer fire%s — run "
                  "--baseline-update to prune:"
                  % (len(stale),
                     "y" if len(stale) == 1 else "ies",
                     "s" if len(stale) == 1 else ""))
            for k in stale:
                print("  stale: %s" % k)
        print("mxlint: %d new, %d baselined, %d stale, "
              "%d file(s) with findings"
              % (len(result.new), len(result.baselined), len(stale),
                 len({d.path for d in result.diags})))
    return result.exit_code


if __name__ == "__main__":
    sys.exit(main())
