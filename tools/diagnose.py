#!/usr/bin/env python
"""Diagnose script: OS / hardware / python / pip / mxnet_tpu / device checks.

Parity: /root/reference/tools/diagnose.py (its output is "a very good hint
to issue/problem"). TPU-native differences: the device section probes the
PJRT backend (with a timeout, since a tunneled TPU can hang instead of
failing), the mxnet section reports the typed flag registry instead of
env-var sprawl, and network checks default OFF (TPU pods are commonly
egress-less; the reference pinged mxnet.io et al. by default).

Usage: python tools/diagnose.py [--python 1] [--pip 1] [--mxnet 1]
       [--os 1] [--hardware 1] [--device 1] [--network 0]
       [--timeout 20] [--region us]
"""
import argparse
import os
import platform
import socket
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REGION_URLS = {
    "us": ["https://pypi.org", "https://github.com"],
    "cn": ["https://pypi.tuna.tsinghua.edu.cn", "https://gitee.com"],
}


def parse_args():
    p = argparse.ArgumentParser(
        formatter_class=argparse.ArgumentDefaultsHelpFormatter,
        description="Diagnose the current system for bug reports.")
    for choice in ("python", "pip", "mxnet", "os", "hardware", "device"):
        p.add_argument("--" + choice, default=1, type=int,
                       help="Diagnose %s." % choice)
    p.add_argument("--network", default=0, type=int,
                   help="Diagnose network (off by default: TPU hosts are "
                        "often egress-less).")
    p.add_argument("--region", default="us", choices=list(REGION_URLS),
                   help="Url region for the network test.")
    p.add_argument("--timeout", default=20, type=int,
                   help="Seconds before a probe (device init, url) is "
                        "declared hung.")
    return p.parse_args()


def check_python():
    print("----------Python Info----------")
    print("Version      :", platform.python_version())
    print("Compiler     :", platform.python_compiler())
    print("Build        :", platform.python_build())
    print("Arch         :", platform.architecture())


def check_pip():
    print("------------Pip Info-----------")
    try:
        import pip
        print("Version      :", pip.__version__)
        print("Directory    :", os.path.dirname(pip.__file__))
    except ImportError:
        print("No corresponding pip install for current python.")


def check_mxnet():
    print("----------mxnet_tpu Info-----------")
    try:
        t0 = time.time()
        import mxnet_tpu as mx
        print("Version      :", mx.__version__)
        print("Directory    :", os.path.dirname(mx.__file__))
        print("Import time  : %.3f s" % (time.time() - t0))
        for name in ("jax", "jaxlib", "flax", "optax", "numpy"):
            try:
                m = __import__(name)
                print("%-13s: %s" % (name, getattr(m, "__version__", "?")))
            except ImportError:
                print("%-13s: not installed" % name)
        from mxnet_tpu.config import flags, describe
        non_default = {d["name"]: getattr(flags, d["name"])
                       for d in describe()
                       if getattr(flags, d["name"]) != d["default"]}
        print("Flags (non-default):", non_default or "none")
    except ImportError as e:
        print("No mxnet_tpu installed:", e)
    except Exception as e:  # pragma: no cover - env-specific
        print("An error occurred trying to import mxnet_tpu.")
        print(e)


def check_os():
    print("----------System Info----------")
    print("Platform     :", platform.platform())
    print("system       :", platform.system())
    print("node         :", platform.node())
    print("release      :", platform.release())
    print("version      :", platform.version())


def check_hardware():
    print("----------Hardware Info----------")
    print("machine      :", platform.machine())
    print("processor    :", platform.processor())
    if sys.platform.startswith("linux"):
        try:
            out = subprocess.check_output(["lscpu"], text=True)
            for line in out.splitlines():
                if line and not line.startswith("Flags"):
                    print(line)
        except Exception:
            pass


def check_device(timeout):
    """Probe the PJRT backend in a subprocess so a hung tunnel cannot hang
    the diagnosis itself (the reference had no analog: CUDA init fails
    fast, a tunneled TPU blocks)."""
    print("----------Device Info----------")
    code = ("import jax, json; d = jax.devices(); "
            "print(json.dumps([{'kind': x.device_kind, "
            "'platform': x.platform, 'id': x.id} for x in d]))")
    t0 = time.time()
    try:
        out = subprocess.run([sys.executable, "-c", code], timeout=timeout,
                             capture_output=True, text=True)
        dt = time.time() - t0
        tail = [ln for ln in out.stdout.strip().splitlines() if ln]
        if out.returncode == 0 and tail:
            print("Devices      :", tail[-1])
            print("Init time    : %.1f s" % dt)
        else:
            print("Device init FAILED (rc=%d) after %.1f s" % (
                out.returncode, dt))
            if out.stderr:
                print(out.stderr.strip().splitlines()[-1])
    except subprocess.TimeoutExpired:
        print("Device init HUNG (> %d s) — tunnel/backend unreachable"
              % timeout)
    print("JAX_PLATFORMS:", os.environ.get("JAX_PLATFORMS", "<unset>"))


def test_connection(name, url, timeout):
    from urllib.request import urlopen
    from urllib.parse import urlparse
    try:
        ip = socket.gethostbyname(urlparse(url).netloc)
        t0 = time.time()
        urlopen(url, timeout=timeout)
        print("Timing for %s: %s, DNS: %s, LOAD: %.4f sec."
              % (name, url, ip, time.time() - t0))
    except Exception as e:
        print("Error open %s: %s %s, DNS finished in %s sec."
              % (name, url, e, time.time() - t0 if "t0" in dir() else "?"))


def check_network(args):
    print("----------Network Test----------")
    socket.setdefaulttimeout(10)
    for url in REGION_URLS[args.region]:
        test_connection(url, url, args.timeout)


if __name__ == "__main__":
    args = parse_args()
    if args.python:
        check_python()
    if args.pip:
        check_pip()
    if args.mxnet:
        check_mxnet()
    if args.os:
        check_os()
    if args.hardware:
        check_hardware()
    if args.device:
        check_device(args.timeout)
    if args.network:
        check_network(args)
