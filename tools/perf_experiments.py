"""Single-chip ResNet-50 perf experiments: where does the step time go?

Runs the fused train step at several configurations and prints a table:
  fwd-only vs full step, batch scaling, grouped scan dispatch, optional
  XLA-flag variants (set XLA_FLAGS in the shell — it must precede jax
  init). Timing = forced host fetch after N steps (same methodology as
  bench.py).

Usage:  python tools/perf_experiments.py [--steps 20]
        [--cases fwd128,step128,step256,scan128x10]
        # fwd<N> = fwd-only batch N; step<N> = full train step;
        # scan<N>x<K> = fit(steps_per_dispatch=K): K steps per dispatch
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run(batch, steps, fwd_only=False, scan_k=0):
    import jax
    import jax.numpy as jnp
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import models
    from mxnet_tpu.io import DataBatch, DataDesc

    dev = jax.devices()[0]
    ctx = mx.tpu() if dev.platform != "cpu" else mx.cpu()
    sym = models.resnet_symbol(num_classes=1000, num_layers=50)
    rng = np.random.RandomState(0)
    data_nd = mx.nd.array(rng.randn(batch, 3, 224, 224).astype(np.float32),
                          ctx=ctx)
    label_nd = mx.nd.array(rng.randint(0, 1000, (batch,)).astype(np.float32),
                           ctx=ctx)
    batch_obj = DataBatch(data=[data_nd], label=[label_nd])

    mod = mx.mod.Module(sym, context=ctx)

    if not fwd_only:
        # Route EVERY train case through fit() so each case reuses the ONE
        # donating jitted program bench.py measures (forward_backward would
        # compile a second, non-donating variant: minutes of wasted tunnel
        # compile and not the benched path). scan_k<=1 -> per-step dispatch.
        scan_k = max(scan_k, 1)
        if steps % scan_k:
            # fit's grouped path only engages for FULL groups of K; an
            # undersized tail falls back to per-step and the printed number
            # would silently mix the two dispatch modes
            raise ValueError("--steps %d not divisible by scan K=%d: the "
                             "tail batches would run per-step" % (steps,
                                                                  scan_k))
        # grouped dispatch through the product API, bench.py-style timing
        class _It:
            provide_data = [DataDesc("data", (batch, 3, 224, 224))]
            provide_label = [DataDesc("softmax_label", (batch,))]
            batch_size = batch

            def __iter__(self):
                return iter([batch_obj] * steps)

            def reset(self):
                pass

        t_k = []

        def cb(epoch, symbol, a, b):
            jax.device_get(mod._exec.arg_dict[mod._param_names[0]]._data)
            t_k.append(time.perf_counter())

        mod.fit(_It(), num_epoch=3, eval_metric=None, kvstore="tpu_sync",
                optimizer="sgd",
                optimizer_params={"learning_rate": 0.05, "momentum": 0.9,
                                  "multi_precision": True},
                initializer=mx.initializer.Xavier(factor_type="in",
                                                  magnitude=2.0),
                steps_per_dispatch=scan_k, epoch_end_callback=cb)
        dt = t_k[-1] - t_k[0]
        n = steps * (len(t_k) - 1)
        return dt / n * 1e3, batch * n / dt
    mod.bind([DataDesc("data", (batch, 3, 224, 224))],
             [DataDesc("softmax_label", (batch,))],
             for_training=False)
    mod.init_params(mx.initializer.Xavier(factor_type="in", magnitude=2.0))

    def one_step():
        mod.forward(batch_obj, is_train=False)

    def force():
        arr = mod.get_outputs()[0]._data
        return float(np.asarray(jax.device_get(arr)).ravel()[0])

    one_step(); force()          # compile
    t0 = time.perf_counter()
    for _ in range(steps):
        one_step()
    force()
    dt = time.perf_counter() - t0
    return dt / steps * 1e3, batch * steps / dt


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--cases", default="fwd128,step128,step256")
    args = ap.parse_args()

    for case in args.cases.split(","):
        case = case.strip()
        if case.startswith("scan"):
            b, k = (int(x) for x in case[4:].split("x"))
            ms, img_s = run(b, args.steps, scan_k=k)
            print("CASE scan(K=%-3d) b=%-4d %8.2f ms/step %10.1f img/s"
                  % (k, b, ms, img_s), flush=True)
            continue
        fwd = case.startswith("fwd")
        b = int(case.replace("fwd", "").replace("step", ""))
        ms, img_s = run(b, args.steps, fwd_only=fwd)
        kind = "fwd-only" if fwd else "train"
        print("CASE %-10s b=%-4d %8.2f ms/step %10.1f img/s"
              % (kind, b, ms, img_s), flush=True)


if __name__ == "__main__":
    main()
