#!/usr/bin/env python
"""Create image RecordIO datasets (parity: reference tools/im2rec.py —
make-list + pack modes, multiprocess encode workers).

Two modes, same CLI as the reference:

  # 1) build .lst index files from an image folder
  python tools/im2rec.py --list --recursive myprefix path/to/images

  # 2) pack a .lst into prefix.rec/prefix.idx (JPEG-encoded, resized)
  python tools/im2rec.py --resize 256 --quality 95 --num-thread 8 \
      myprefix path/to/images

The .rec produced feeds ImageRecordIter / ImageRecordDataset directly.
"""
import argparse
import multiprocessing
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def list_image(root, recursive, exts):
    """Yield (index, relpath, label) with one label per leaf directory."""
    i = 0
    if recursive:
        cat = {}
        for path, dirs, files in os.walk(root, followlinks=True):
            dirs.sort()
            files.sort()
            for fname in files:
                fpath = os.path.join(path, fname)
                suffix = os.path.splitext(fname)[1].lower()
                if os.path.isfile(fpath) and suffix in exts:
                    if path not in cat:
                        cat[path] = len(cat)
                    yield (i, os.path.relpath(fpath, root), cat[path])
                    i += 1
    else:
        for fname in sorted(os.listdir(root)):
            fpath = os.path.join(root, fname)
            suffix = os.path.splitext(fname)[1].lower()
            if os.path.isfile(fpath) and suffix in exts:
                yield (i, os.path.relpath(fpath, root), 0)
                i += 1


def write_list(path_out, image_list):
    with open(path_out, "w") as f:
        for item in image_list:
            line = "%d\t" % item[0]
            for j in item[2:]:
                line += "%f\t" % j
            line += "%s\n" % item[1]
            f.write(line)


def make_list(args):
    image_list = list(list_image(args.root, args.recursive, args.exts))
    if args.shuffle:
        random.seed(100)
        random.shuffle(image_list)
    n = len(image_list)
    chunk_size = (n + args.chunks - 1) // args.chunks
    for i in range(args.chunks):
        chunk = image_list[i * chunk_size:(i + 1) * chunk_size]
        str_chunk = ".%d" % i if args.chunks > 1 else ""
        sep = int(chunk_size * args.train_ratio)
        sep_test = int(chunk_size * args.test_ratio)
        if args.train_ratio == 1.0:
            write_list(args.prefix + str_chunk + ".lst", chunk)
        else:
            if args.test_ratio:
                write_list(args.prefix + str_chunk + "_test.lst",
                           chunk[:sep_test])
            if args.train_ratio + args.test_ratio < 1.0:
                write_list(args.prefix + str_chunk + "_val.lst",
                           chunk[sep + sep_test:])
            write_list(args.prefix + str_chunk + "_train.lst",
                       chunk[sep_test:sep + sep_test])


def read_list(path_in):
    """Yield (index, path, label...) tuples from a .lst file."""
    with open(path_in) as f:
        for line_i, line in enumerate(f):
            line = [i.strip() for i in line.strip().split("\t")]
            if len(line) < 3:
                print("lst should have at least 3 parts, skip line %d"
                      % line_i)
                continue
            try:
                yield (int(line[0]),) + tuple(float(x) for x in line[1:-1]) \
                    + (line[-1],)
            except ValueError:
                print("parsing lst met error for line %d: %s"
                      % (line_i, line))


def image_encode(args, i, item, q_out):
    import cv2
    import numpy as np
    from mxnet_tpu import recordio
    fullpath = os.path.join(args.root, item[-1])

    if len(item) > 3 and args.pack_label:
        header = recordio.IRHeader(0, np.asarray(item[1:-1], np.float32),
                                   item[0], 0)
    else:
        header = recordio.IRHeader(0, item[1], item[0], 0)

    if args.pass_through:
        try:
            with open(fullpath, "rb") as fin:
                img = fin.read()
            q_out.put((i, recordio.pack(header, img), item))
        except Exception as e:
            q_out.put((i, None, item))
            print("pack_img error:", item[-1], e)
        return

    img = cv2.imread(fullpath, args.color)
    if img is None:
        print("imread read blank (None) image for file:", fullpath)
        q_out.put((i, None, item))
        return
    if args.center_crop:
        if img.shape[0] > img.shape[1]:
            margin = (img.shape[0] - img.shape[1]) // 2
            img = img[margin:margin + img.shape[1], :]
        else:
            margin = (img.shape[1] - img.shape[0]) // 2
            img = img[:, margin:margin + img.shape[0]]
    if args.resize:
        if img.shape[0] > img.shape[1]:
            newsize = (args.resize,
                       img.shape[0] * args.resize // img.shape[1])
        else:
            newsize = (img.shape[1] * args.resize // img.shape[0],
                       args.resize)
        img = cv2.resize(img, newsize)
    try:
        s = recordio.pack_img(header, img, quality=args.quality,
                              img_fmt=args.encoding)
    except Exception as e:
        print("pack_img failed:", fullpath, e)
        q_out.put((i, None, item))
        return
    q_out.put((i, s, item))


def read_worker(args, q_in, q_out):
    while True:
        deq = q_in.get()
        if deq is None:
            break
        i, item = deq
        image_encode(args, i, item, q_out)


def write_worker(q_out, fname, working_dir):
    from mxnet_tpu import recordio
    pre_time = time.time()
    count = 0
    fname = os.path.basename(fname)
    fname_rec = os.path.splitext(fname)[0] + ".rec"
    fname_idx = os.path.splitext(fname)[0] + ".idx"
    record = recordio.MXIndexedRecordIO(
        os.path.join(working_dir, fname_idx),
        os.path.join(working_dir, fname_rec), "w")
    buf = {}
    more = True
    while more:
        deq = q_out.get()
        if deq is not None:
            i, s, item = deq
            buf[i] = (s, item)
        else:
            more = False
        while count in buf:
            s, item = buf[count]
            del buf[count]
            if s is not None:
                record.write_idx(item[0], s)
            if count % 1000 == 0:
                cur_time = time.time()
                print("time:", cur_time - pre_time, " count:", count)
                pre_time = cur_time
            count += 1
    record.close()


def pack(args, fname):
    q_in = [multiprocessing.Queue(1024) for _ in range(args.num_thread)]
    q_out = multiprocessing.Queue(1024)
    read_processes = [
        multiprocessing.Process(target=read_worker,
                                args=(args, q_in[i], q_out))
        for i in range(args.num_thread)]
    for p in read_processes:
        p.start()
    write_process = multiprocessing.Process(
        target=write_worker, args=(q_out, fname, args.working_dir))
    write_process.start()
    for i, item in enumerate(read_list(fname)):
        q_in[i % len(q_in)].put((i, item))
    for q in q_in:
        q.put(None)
    for p in read_processes:
        p.join()
    q_out.put(None)
    write_process.join()


def parse_args(argv=None):
    parser = argparse.ArgumentParser(
        description="Create an image list and/or RecordIO database")
    parser.add_argument("prefix",
                        help="prefix of input/output lst and rec files")
    parser.add_argument("root", help="path to folder containing images")
    cgroup = parser.add_argument_group("Options for creating image lists")
    cgroup.add_argument("--list", action="store_true",
                        help="make a list instead of a record database")
    cgroup.add_argument("--exts", nargs="+",
                        default=[".jpeg", ".jpg", ".png"])
    cgroup.add_argument("--chunks", type=int, default=1)
    cgroup.add_argument("--train-ratio", type=float, default=1.0)
    cgroup.add_argument("--test-ratio", type=float, default=0)
    cgroup.add_argument("--recursive", action="store_true",
                        help="one label per leaf folder")
    cgroup.add_argument("--no-shuffle", dest="shuffle",
                        action="store_false")
    rgroup = parser.add_argument_group("Options for creating database")
    rgroup.add_argument("--pass-through", action="store_true",
                        help="skip transcoding, pack original bytes")
    rgroup.add_argument("--resize", type=int, default=0)
    rgroup.add_argument("--center-crop", action="store_true")
    rgroup.add_argument("--quality", type=int, default=95)
    rgroup.add_argument("--num-thread", type=int, default=1)
    rgroup.add_argument("--color", type=int, default=1,
                        choices=[-1, 0, 1])
    rgroup.add_argument("--encoding", type=str, default=".jpg",
                        choices=[".jpg", ".png"])
    rgroup.add_argument("--pack-label", action="store_true")
    args = parser.parse_args(argv)
    args.prefix = os.path.abspath(args.prefix)
    args.root = os.path.abspath(args.root)
    return args


def main(argv=None):
    args = parse_args(argv)
    if args.list:
        make_list(args)
        return
    # a directory prefix means "pack every .lst inside it"
    if os.path.isdir(args.prefix):
        args.working_dir = args.prefix
    else:
        args.working_dir = os.path.dirname(args.prefix)
    files = [os.path.join(args.working_dir, f)
             for f in os.listdir(args.working_dir)
             if os.path.isfile(os.path.join(args.working_dir, f))]
    count = 0
    for fname in files:
        if fname.startswith(args.prefix) and fname.endswith(".lst"):
            print("Creating .rec file from", fname, "in", args.working_dir)
            count += 1
            pack(args, fname)
    if not count:
        print("Did not find and list file with prefix %s" % args.prefix)


if __name__ == "__main__":
    main()
