"""Sparse linear-regression end-to-end benchmark with phase breakdown.

Parity: /root/reference/benchmark/python/sparse/sparse_end2end.py (the
BASELINE.md measurement-tools row "sparse op + end-to-end benchmarks").
Same shape: LibSVM data through a sparse embedding/dot linear model with a
row_sparse weight pushed/pulled through a kvstore, measuring total
samples/sec plus what the reference's --measure-only io/compute/
communication split reports — here as per-phase timings taken in one run
(io = iterator next, comm = kvstore push/pull + row_sparse_pull,
compute = forward/backward/update minus comm).

One JSON line:

    {"metric": "sparse_linear_samples_per_sec", "value": ..., "io_ms": ...,
     "comm_ms": ..., "compute_ms": ...}

Usage: python tools/sparse_end2end.py [--num-features 100000] [--nnz 30]
       [--batch-size 512] [--num-batch 50] [--kvstore local]
       [--platform cpu]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def make_libsvm(path, n, dim, nnz, seed=0):
    import numpy as np
    rng = np.random.RandomState(seed)
    w = rng.randn(dim)
    with open(path, "w") as f:
        for _ in range(n):
            idx = rng.choice(dim, min(nnz, dim), replace=False)
            val = rng.randn(len(idx))
            y = float(np.dot(w[idx], val))
            f.write("%.4f %s\n" % (y, " ".join(
                "%d:%.4f" % (i, v) for i, v in sorted(zip(idx, val)))))
    return path


def main():
    p = argparse.ArgumentParser(
        formatter_class=argparse.ArgumentDefaultsHelpFormatter,
        description="sparse linear regression end-to-end benchmark")
    p.add_argument("--num-features", type=int, default=100000)
    p.add_argument("--nnz", type=int, default=30,
                   help="non-zeros per example")
    p.add_argument("--batch-size", type=int, default=512)
    p.add_argument("--num-batch", type=int, default=50)
    p.add_argument("--num-epoch", type=int, default=2,
                   help="epoch 0 warms compiles; later epochs are timed")
    p.add_argument("--kvstore", default="local")
    p.add_argument("--platform", default=None, choices=[None, "cpu"])
    args = p.parse_args()
    if args.num_epoch < 2:
        p.error("--num-epoch must be >= 2 (epoch 0 is compile warmup; "
                "timing starts at epoch 1)")
    if args.platform == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")

    import numpy as np
    import jax
    import mxnet_tpu as mx

    n_examples = args.batch_size * args.num_batch
    path = make_libsvm("/tmp/mxtpu_sparse_e2e.libsvm", n_examples,
                       args.num_features, args.nnz)

    kv = mx.kv.create(args.kvstore)
    it = mx.io.LibSVMIter(data_libsvm=path,
                          data_shape=(args.num_features,),
                          batch_size=args.batch_size)

    on_tpu = jax.devices()[0].platform != "cpu"
    ctx = mx.tpu() if on_tpu else mx.cpu()
    weight = mx.nd.sparse.zeros("row_sparse", (args.num_features, 1))
    kv.init("w", weight)
    optimizer = mx.optimizer.create("adagrad", learning_rate=0.1)
    kv.set_optimizer(optimizer)

    io_s = comm_s = 0.0
    t_total0 = None
    n_seen = 0
    for epoch in range(args.num_epoch):
        it.reset()
        if epoch == 1:
            t_total0 = time.perf_counter()
            io_s = comm_s = 0.0
        while True:
            t0 = time.perf_counter()
            try:
                batch = next(it)
            except StopIteration:
                break
            io_s += time.perf_counter() - t0

            csr = batch.data[0]
            row_ids = mx.nd.array(
                np.unique(csr.indices.asnumpy()), dtype="int64")
            t0 = time.perf_counter()
            kv.row_sparse_pull("w", out=weight, row_ids=row_ids)
            comm_s += time.perf_counter() - t0

            # forward/backward by hand: pred = X.w ; grad = X^T (pred - y)/b
            pred = mx.nd.sparse.dot(csr, weight)
            err = pred - batch.label[0].reshape((-1, 1))
            grad_dense = mx.nd.sparse.dot(csr, err / args.batch_size,
                                          transpose_a=True)
            grad = grad_dense.tostype("row_sparse")

            t0 = time.perf_counter()
            kv.push("w", grad)
            comm_s += time.perf_counter() - t0
            if epoch > 0:
                n_seen += args.batch_size
    mx.nd.waitall()
    total = time.perf_counter() - t_total0
    compute = max(total - io_s - comm_s, 0.0)
    timed_batches = args.num_batch * (args.num_epoch - 1)
    print(json.dumps({
        "metric": "sparse_linear_samples_per_sec",
        "value": round(n_seen / total, 1), "unit": "samples/s",
        "num_features": args.num_features, "batch": args.batch_size,
        "kvstore": args.kvstore,
        "io_ms": round(io_s / timed_batches * 1e3, 2),
        "comm_ms": round(comm_s / timed_batches * 1e3, 2),
        "compute_ms": round(compute / timed_batches * 1e3, 2),
        "device": jax.devices()[0].device_kind,
    }))


if __name__ == "__main__":
    main()
