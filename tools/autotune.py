"""Offline auto-tuner CLI for the Pallas kernel tier.

Enumerates the bounded config space of each requested (op, shape-bucket,
dtype), ranks it — on-chip wall time when an accelerator is attached,
the chip-free learned cost model otherwise — and (with --update-cache)
persists the winners to the versioned tuning cache the dispatch layer
consults at trace time (tools/kernel_tuning.json by default).

    # tune one op on one shape bucket, chip-free
    python tools/autotune.py --op bn_act --shape 8192x4096 \
        --dtype bfloat16 --chip-free

    # derive the shape list from the benched ResNet-50 fused step and
    # commit the winners (shrink-only growth guard: re-tuning a key the
    # cache already holds needs --allow-rewrite)
    python tools/autotune.py --shapes-from-bench --chip-free --update-cache

    # close the cost-model loop: fit the chip-free linear model on the
    # wall times an earlier ON-CHIP tuning run logged (the timing JSONL
    # mxnet_tpu/tune/timings.py appends), report before/after ranking
    # agreement, and persist the weights default_model() will pick up
    python tools/autotune.py --recalibrate \
        --timings work/kernel_timings.jsonl \
        --save-model tools/kernel_cost_model.json

Shape syntax mirrors the cache key's middle segment: ``RxS`` for one
operand, comma-separated for several (take_rows: ``65536x512,1024``).
Chip-free rankings are deterministic (ties broken by config key), so two
runs over the same inputs produce byte-identical caches — that property
is tested in tests/test_autotune.py.
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def parse_shapes(spec):
    """'8192x4096' -> ((8192, 4096),); '65536x512,1024' -> two operands."""
    shapes = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        shapes.append(tuple(int(d) for d in part.split("x")))
    if not shapes:
        raise ValueError("empty shape spec %r" % (spec,))
    return tuple(shapes)


def parse_cache_key(key):
    """Invert ``cache.shape_bucket_key``: 'op|RxS|dtype' -> task tuple."""
    op, shapes, dtype = key.split("|")
    return op, parse_shapes(shapes), dtype


def bench_step_tasks(batch):
    """Trace the benched ResNet-50 fused step under tier=auto and return
    the (op, shapes, dtype) buckets the dispatch layer actually asked
    for — tuning exactly what the hot path will look up."""
    from diagnose_step_hlo import build_fused, lower_step
    from mxnet_tpu import config
    from mxnet_tpu.kernels import tier

    with config.override(kernel_tier="auto"):
        tier.reset_stats()
        mod = build_fused(batch)
        lower_step(mod)          # chip-free trace records dispatch keys
        keys = sorted(tier.stats()["configs"])
    return [parse_cache_key(k) for k in keys]


def _pct(x):
    return "%.1f%%" % (100.0 * x)


def recalibrate_main(args):
    """``--recalibrate``: measured timings -> LinearCostModel.fit ->
    before/after ranking-fidelity report (ISSUE 7 / ROADMAP item 1)."""
    from mxnet_tpu.tune import cost_model as _cm
    from mxnet_tpu.tune import timings as _timings

    path = args.timings or _timings.timings_path()
    if not path or not os.path.exists(path):
        print("error: no timing log%s — run the tuner with a chip "
              "attached first (it appends to MXNET_KERNEL_TIMINGS or "
              "$MXNET_TELEMETRY_DIR/kernel_timings.jsonl), or pass "
              "--timings PATH" % (" at %s" % path if path else ""),
              file=sys.stderr)
        return 2
    rows, skipped = _timings.load(path)
    if skipped:
        print("(skipped %d malformed timing row(s))" % skipped)
    if not rows:
        print("error: %s holds no usable timing rows" % path,
              file=sys.stderr)
        return 2
    fitted, report = _timings.recalibrate(rows)
    before, after = report["before"], report["after"]
    print("recalibrated on %d measured row(s), %d task(s), from %s"
          % (report["rows"], report["tasks"], path))
    print("ranking agreement vs measured ground truth "
          "(before -> after fit):")
    print("  pairwise  %s -> %s" % (_pct(before["pairwise"]),
                                    _pct(after["pairwise"])))
    print("  top-1     %s -> %s" % (_pct(before["top1"]),
                                    _pct(after["top1"])))
    for key in sorted(after["tasks"]):
        b, a = before["tasks"][key], after["tasks"][key]
        print("  %-40s %2d cfgs  pairwise %s -> %s  top1 %s -> %s"
              % (key, a["n"], _pct(b["pairwise"]), _pct(a["pairwise"]),
                 "y" if b["top1"] else "n", "y" if a["top1"] else "n"))
    print("weights:")
    for k in _cm.FEATURE_NAMES:
        print("  %-18s %12.6g -> %12.6g"
              % (k, report["weights_before"][k],
                 report["weights_after"][k]))
    if args.save_model:
        p = _cm.save_weights(fitted, args.save_model)
        print("wrote recalibrated weights to %s (set "
              "MXNET_KERNEL_COST_MODEL=%s to rank with them)" % (p, p))
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="tune Pallas kernel-tier tile configs")
    ap.add_argument("--op", action="append", default=[],
                    help="kernel op name (repeatable); requires --shape")
    ap.add_argument("--shape", action="append", default=[],
                    help="shape spec like 8192x4096 (repeatable; paired "
                         "with --op by cross product)")
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--shapes-from-bench", action="store_true",
                    help="derive (op, shape, dtype) tasks from the "
                         "benched ResNet-50 fused step (chip-free trace)")
    ap.add_argument("--batch", type=int, default=128,
                    help="bench batch for --shapes-from-bench")
    ap.add_argument("--chip-free", action="store_true",
                    help="rank with the static cost model even when an "
                         "accelerator is attached")
    ap.add_argument("--iters", type=int, default=20,
                    help="timing iterations per config (on-chip mode)")
    ap.add_argument("--top", type=int, default=5,
                    help="ranking rows to print per task")
    ap.add_argument("--update-cache", action="store_true",
                    help="merge winners into the tuning cache")
    ap.add_argument("--allow-rewrite", action="store_true",
                    help="permit changing configs of committed keys "
                         "(growth guard override)")
    ap.add_argument("--cache", default=None,
                    help="cache path (default: MXNET_KERNEL_TUNING_CACHE "
                         "or tools/kernel_tuning.json)")
    ap.add_argument("--recalibrate", action="store_true",
                    help="fit the chip-free cost model on the measured "
                         "kernel-timing log and report before/after "
                         "ranking agreement (no tuning tasks needed)")
    ap.add_argument("--timings", default=None,
                    help="timing JSONL for --recalibrate (default: "
                         "MXNET_KERNEL_TIMINGS or "
                         "$MXNET_TELEMETRY_DIR/kernel_timings.jsonl)")
    ap.add_argument("--save-model", default=None,
                    help="with --recalibrate: persist the fitted weights "
                         "to this JSON (consulted via "
                         "MXNET_KERNEL_COST_MODEL)")
    args = ap.parse_args(argv)

    if args.recalibrate:
        return recalibrate_main(args)

    from mxnet_tpu.tune import cache as tcache
    from mxnet_tpu.tune import tuner

    tasks = []
    if args.shapes_from_bench:
        tasks.extend(bench_step_tasks(args.batch))
    for op in args.op:
        if not args.shape:
            ap.error("--op needs at least one --shape")
        for spec in args.shape:
            tasks.append((op, parse_shapes(spec), args.dtype))
    if not tasks:
        ap.error("nothing to tune: pass --op/--shape or "
                 "--shapes-from-bench")

    chip_free = args.chip_free or None   # None -> auto (cpu => chip-free)
    new_entries = {}
    for op, shapes, dtype in tasks:
        result = tuner.tune(op, shapes, dtype, chip_free=chip_free,
                            iters=args.iters)
        print("%s  (%d candidates, %s)" % (
            result["key"], len(result["ranking"]), result["source"]))
        for row in result["ranking"][:args.top]:
            print("  %10.2f us  %s" % (row["score_us"], row["config"]))
        best = result["best"]
        new_entries[result["key"]] = {
            "op": op, "dtype": dtype,
            "shapes": result["shapes"],
            "config": best["config"],
            "score_us": best["score_us"],
            "source": best["source"],
            "device_kind": result["device_kind"],
        }

    if not args.update_cache:
        print("(dry run: pass --update-cache to persist %d winner(s))"
              % len(new_entries))
        return 0

    path = args.cache or tcache.default_cache_path()
    cache = tcache.TuningCache.load(path)
    if not cache.version_ok:
        print("cache %s has a stale format/version — rebuilding it "
              "wholesale" % path)
        cache = tcache.TuningCache(path=path)
    try:
        cache.update_entries(new_entries,
                             allow_rewrite=args.allow_rewrite)
    except tcache.CacheRewriteError as e:
        print("error: %s" % e, file=sys.stderr)
        return 2
    cache.save(path)
    tcache.invalidate_default()
    print("wrote %d entr%s to %s (fingerprint %s)"
          % (len(cache.entries),
             "y" if len(cache.entries) == 1 else "ies",
             path, cache.fingerprint()))
    return 0


if __name__ == "__main__":
    sys.exit(main())
