#!/bin/bash
# Extra on-chip phases beyond tools/onchip_session.sh — run by
# tools/chip_watcher.sh right after the main session. Each phase guards
# its own tunnel probe and logs incrementally, so a mid-session tunnel
# loss still leaves earlier results.
#
#   bash tools/onchip_extra.sh [logdir]
#
# Phase A  int8 microbench   — is int8 actually faster than bf16 on the
#                              MXU? (VERDICT r4 item 5)
# Phase B  LSTM re-capture   — post-projection-hoist tokens/s (item 4)
# Phase C  RecordIO bench    — decode->staging->H2D overlap vs synthetic
#                              (item 3; BENCH_RECORDIO=1)
# Phase D  memory/donation   — compiled memory_analysis + donation alias
#                              check on the real PJRT plugin (item 2c)
set -u
cd "$(dirname "$0")/.."
LOG=${1:-/tmp/onchip}
mkdir -p "$LOG"

probe() {
  timeout 90 python -c "import jax; print(jax.devices())" >/dev/null 2>&1
}

phase() {  # phase <name> <timeout> <cmd...>
  local name=$1 tmo=$2; shift 2
  if ! probe; then
    echo "[extra] tunnel down before $name — stopping"; exit 2
  fi
  echo "[extra] phase $name"
  timeout "$tmo" "$@" 2>&1 | tee "$LOG/$name.log" | grep -v -E "WARN|axon_"
}

phase int8 1800 python -u tools/microbench_int8.py --iters 50
phase int8serve 1800 python -u tools/serve_int8_onchip.py --iters 30
phase lstm 1800 python -u tools/bench_lstm.py --steps 30
phase transformer 1800 python -u tools/bench_transformer.py --steps 20
phase recordio 3600 env BENCH_RECORDIO=1 BENCH_K=30 python -u bench.py
phase memdonation 1800 python -u tools/diagnose_step_hlo.py --on-chip

echo "[extra] done — logs in $LOG"
