"""Inference throughput across the model zoo — the reference's
example/image-classification/benchmark_score.py (source of the inference
rows in docs/faq/perf.md:169-194 / BASELINE.md).

Symbolic models run through the bound Executor (one fused XLA inference
program, bf16 optional); gluon zoo models run hybridized. One JSON line
per (model, batch):

    {"metric": "inference_img_per_sec", "model": "resnet-50", ...}
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _symbolic(name, num_layers):
    from mxnet_tpu import models
    if name == "resnet":
        return models.resnet_symbol(num_classes=1000, num_layers=num_layers)
    if name == "inception-v3":
        return models.inception_v3_symbol(num_classes=1000)
    if name == "alexnet":
        return models.alexnet_symbol(num_classes=1000)
    raise ValueError(name)


def score(model="resnet-50", batch=32, steps=20, dtype="float32"):
    """dtype: float32 or bfloat16 (symbolic models; gluon zoo casts the
    whole block)."""
    import numpy as np
    import jax
    import mxnet_tpu as mx

    on_tpu = jax.devices()[0].platform != "cpu"
    ctx = mx.tpu() if on_tpu else mx.cpu()
    shape = (3, 299, 299) if model == "inception-v3" else (3, 224, 224)

    name, _, layers = model.partition("-")
    if name == "inception":
        sym = _symbolic("inception-v3", 0)
    elif name in ("resnet", "alexnet"):
        sym = _symbolic(name, int(layers) if layers else 50)
    else:
        # gluon zoo path (vgg16, mobilenet..., densenet..., squeezenet...)
        from mxnet_tpu.gluon.model_zoo import vision
        net = vision.get_model(model, pretrained=False)
        net.initialize(mx.initializer.Xavier(), ctx=ctx)
        if dtype != "float32":
            net.cast(dtype)
        net.hybridize(static_alloc=True)
        x = mx.nd.array(np.random.rand(batch, *shape).astype("f4"),
                        ctx=ctx, dtype=dtype)
        net(x).wait_to_read()   # compile
        t0 = time.perf_counter()
        for _ in range(steps):
            out = net(x)
        float(np.asarray(jax.device_get(out._data)).ravel()[0])
        dt = time.perf_counter() - t0
        return _line(model, batch, steps, dt, dtype)

    # bf16: params and data in the MXU's native dtype (the reference's
    # fp16 inference rows, perf.md:181-194); BN stats stay f32
    ex = sym.simple_bind(ctx, data=(batch,) + shape, grad_req="null",
                         type_dict={"data": dtype})
    rng = np.random.RandomState(0)
    for k, v in ex.arg_dict.items():
        if k not in ("data", "softmax_label"):
            v[:] = mx.nd.array(rng.uniform(-0.05, 0.05, v.shape)
                               .astype("f4"), ctx=ctx, dtype=v.dtype)
    for k, v in ex.aux_dict.items():
        v[:] = mx.nd.ones(v.shape, ctx=ctx) if k.endswith("var") \
            else mx.nd.zeros(v.shape, ctx=ctx)
    x = mx.nd.array(rng.rand(batch, *shape).astype("f4"), ctx=ctx,
                    dtype=dtype)
    ex.forward(is_train=False, data=x)   # compile
    ex.outputs[0].wait_to_read()
    import jax as _j
    t0 = time.perf_counter()
    for _ in range(steps):
        ex.forward(is_train=False, data=x)
    float(np.asarray(_j.device_get(ex.outputs[0]._data)).ravel()[0])
    dt = time.perf_counter() - t0
    return _line(model, batch, steps, dt, dtype)


def _line(model, batch, steps, dt, dtype):
    import jax
    return {
        "metric": "inference_img_per_sec",
        "model": model,
        "value": round(batch * steps / dt, 2),
        "unit": "img/s",
        "batch": batch,
        "dtype": dtype,
        "step_ms": round(dt / steps * 1e3, 3),
        "device": jax.devices()[0].device_kind,
    }


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--models", default="resnet-50",
                   help="comma list: resnet-50, resnet-152, inception-v3, "
                        "alexnet, or any gluon zoo name (mobilenet1.0...)")
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--dtype", default="float32",
                   choices=["float32", "bfloat16"])
    p.add_argument("--platform", default=None, choices=[None, "cpu"])
    args = p.parse_args()
    if args.platform == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")
    for m in args.models.split(","):
        print(json.dumps(score(m.strip(), args.batch, args.steps,
                               args.dtype)))


if __name__ == "__main__":
    main()
