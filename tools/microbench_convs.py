"""Per-layer conv microbenchmarks: is the MXU actually fast on our convs?

Times representative ResNet-50 conv shapes (fwd only, bf16, batch 128) in
isolation — many iterations per dispatch via lax.scan so host/tunnel latency
is out of the picture — and prints achieved TFLOP/s vs the chip's bf16 peak.
If these hit high MXU efficiency, the train-step gap is elsewhere
(dispatch, BN, bwd, optimizer); if they don't, XLA conv emitters or layout
are the problem.

Usage: python tools/microbench_convs.py [--iters 50] [--batch 128]
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# (name, N-spatial, Cin, Cout, kernel, stride) at batch b, input HxW
CASES = [
    ("stem 7x7/2 3->64 @224", 224, 3, 64, 7, 2),
    ("3x3 64->64 @56", 56, 64, 64, 3, 1),
    ("1x1 64->256 @56", 56, 64, 256, 1, 1),
    ("3x3 128->128 @28", 28, 128, 128, 3, 1),
    ("3x3 256->256 @14", 14, 256, 256, 3, 1),
    ("3x3 512->512 @7", 7, 512, 512, 3, 1),
    ("1x1 2048->1000-ish fc", 0, 2048, 1000, 0, 0),  # dot_general
]


def peak_flops(kind):
    # one shared table: bench.py MFU, this CLI, and the kernel-tier cost
    # model all read mxnet_tpu.perfmodel
    from mxnet_tpu.perfmodel import peak_flops as _pf
    return _pf(kind)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=50)
    ap.add_argument("--batch", type=int, default=128)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    from jax import lax
    import numpy as np

    dev = jax.devices()[0]
    peak = peak_flops(dev.device_kind)
    print("device=%s peak_bf16=%.0f TFLOP/s batch=%d iters/dispatch=%d"
          % (dev.device_kind, peak / 1e12, args.batch, args.iters), flush=True)
    b = args.batch

    for name, hw, cin, cout, k, s in CASES:
        if hw == 0:  # FC case
            x = jnp.zeros((b, cin), jnp.bfloat16)
            w = jnp.zeros((cout, cin), jnp.bfloat16)
            flops = 2.0 * b * cin * cout

            def body(c, _, w=w):
                return jnp.matmul(c, w.T) @ w, None

            def f(x, w=w):
                out, _ = lax.scan(body, x, None, length=args.iters)
                return out
            flops *= 2  # two matmuls per body to keep carry shape
        else:
            x = jnp.zeros((b, cin, hw, hw), jnp.bfloat16)
            w = jnp.zeros((cout, cin, k, k), jnp.bfloat16)
            pad = (k - 1) // 2
            dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                            ("NCHW", "OIHW", "NCHW"))
            out_hw = (hw + 2 * pad - k) // s + 1
            flops = 2.0 * b * cout * cin * k * k * out_hw * out_hw

            def body(c, _, w=w, s=s, pad=pad, dn=dn):
                o = lax.conv_general_dilated(
                    c, w, window_strides=(s, s), padding=[(pad, pad)] * 2,
                    dimension_numbers=dn)
                # fold output back to input shape so scan carries it
                # (mean over trailing dims -> broadcast): keeps the conv
                # un-elidable without host traffic
                return c + jnp.mean(o).astype(c.dtype), None

            def f(x, w=w):
                out, _ = lax.scan(body, x, None, length=args.iters)
                return out

        jf = jax.jit(f)
        r = jf(x)
        jax.block_until_ready(r)
        t0 = time.perf_counter()
        r = jf(x)
        jax.block_until_ready(r)
        dt = time.perf_counter() - t0
        per_iter = dt / args.iters
        tf = flops / per_iter / 1e12
        print("%-28s %9.3f ms/iter %8.1f TFLOP/s  %5.1f%% peak"
              % (name, per_iter * 1e3, tf, 100.0 * tf / (peak / 1e12)),
              flush=True)


if __name__ == "__main__":
    main()
