"""HLO-level diagnosis of the benched fused ResNet-50 train step.

VERDICT r4 weak #3: the 4x gap between the measured 57.5 ms/step and the
14.5 ms XLA-cost floor was hypothesized (dispatch latency, BN bf16<->f32
round-trips, NCHW transposes) but never evidenced. Most of the evidence
is obtainable WITHOUT the chip from the lowered StableHLO of the exact
program bench.py measures:

* `transpose` op count + total elements moved (layout shuffles);
* `convert` op count broken down by src->dst dtype pair (the BN
  bf16<->f32 statistic boundaries show up as f32<->bf16 pairs);
* convolution / dot_general counts and their element types (MXU diet).

With --on-chip it additionally compiles on the real device and reports
`memory_analysis()` (post-fusion HBM traffic), `input_output_aliases`
(donation survival on the axon PJRT plugin), and the post-optimization
TPU HLO op counts — the numbers the pre-fusion text can only bound.

    python tools/diagnose_step_hlo.py [--batch 128] [--on-chip]
    MXNET_CONV_LAYOUT=NHWC python tools/diagnose_step_hlo.py   # variant
"""
from __future__ import annotations

import argparse
import collections
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_fused(batch):
    import numpy as np
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import models
    from mxnet_tpu.io import DataDesc

    ctx = mx.tpu() if jax.devices()[0].platform != "cpu" else mx.cpu()
    sym = models.resnet_symbol(num_classes=1000, num_layers=50)
    mod = mx.mod.Module(sym, context=ctx)
    mod.bind([DataDesc("data", (batch, 3, 224, 224))],
             [DataDesc("softmax_label", (batch,))])
    mod.init_params(mx.initializer.Xavier(factor_type="in", magnitude=2.0))
    mod.init_optimizer(kvstore="tpu_sync", optimizer="sgd",
                       optimizer_params={"learning_rate": 0.05,
                                         "momentum": 0.9,
                                         "multi_precision": True})
    if mod._fused is None:
        raise RuntimeError("fused step did not engage")
    return mod


def lower_step(mod, donate=False):
    import numpy as _np
    import jax
    import jax.numpy as jnp

    fused = mod._fused
    ex = mod._exec
    npar = len(fused.param_names)
    params, rest = fused.split_args(ex._arg_vals())
    fn = fused._jitted_donate if donate else fused._jitted
    # met_state=None: lower the exact benched program (bench.py runs with
    # eval_metric=None, so no device-metric carry rides the step)
    return fn.lower(
        params, rest, ex._aux_vals(), mod._fused_opt_state, None,
        jnp.zeros((npar,), jnp.float32), jnp.zeros((npar,), jnp.float32),
        _np.float32(1.0), _np.int32(1), jax.random.PRNGKey(0))


def run_sync_trace(mod, batch, steps):
    """Execute a few REAL fused fit steps with the profiler's host-sync
    tracer installed: every blocking d2h/wait prints its Python stack to
    stderr as it happens (who synced, from where), then the aggregate
    counters. An async-loop regression (a stray asnumpy in the hot path)
    shows up as d2h lines per step instead of none."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import profiler
    from mxnet_tpu.io import DataBatch

    rng = np.random.RandomState(0)
    data = mx.nd.array(rng.randn(batch, 3, 224, 224).astype(np.float32),
                       ctx=mx.context.current_context())
    label = mx.nd.array(rng.randint(0, 1000, (batch,)).astype(np.float32),
                        ctx=mx.context.current_context())
    b = DataBatch(data=[data], label=[label])
    mod._fit_step(b)  # compile outside the traced window
    profiler.reset_sync_counters()
    prev = profiler.set_sync_trace(True)
    try:
        for _ in range(steps):
            mod._fit_step(b)
        # one deliberate read — the epoch-boundary-style sync, for contrast
        print("[sync-trace] reading a parameter (expected d2h):",
              flush=True)
        mod._exec.arg_dict[mod._param_names[0]].asnumpy()
    finally:
        profiler.set_sync_trace(prev)
    print("\n== host-sync counters over %d dispatched steps ==" % steps)
    for k, v in profiler.sync_counters().items():
        print("  %-12s %s" % (k, v))


# the counters live in mxnet_tpu.hlo_stats so regression tests
# (tests/test_step_hlo_budget.py) and this CLI share one implementation
from mxnet_tpu.hlo_stats import analyze_stablehlo  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--cpu", action="store_true",
                    help="pin jax to CPU (lowering-only analysis; the "
                         "env var JAX_PLATFORMS=cpu is overridden by "
                         "sitecustomize here, so use this flag)")
    ap.add_argument("--on-chip", action="store_true",
                    help="compile on the device: memory_analysis + "
                         "donation aliases + post-opt HLO counts")
    ap.add_argument("--sync-trace", action="store_true",
                    help="run a few real fit steps with the host-sync "
                         "tracer on: every blocking d2h/wait prints a "
                         "Python stack, then the aggregate counters")
    ap.add_argument("--steps", type=int, default=4,
                    help="steps to run under --sync-trace")
    args = ap.parse_args()

    import jax
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    dev = jax.devices()[0]
    print("device: %s (%s)  batch=%d  conv_layout=%s"
          % (dev.device_kind, dev.platform, args.batch,
             os.environ.get("MXNET_CONV_LAYOUT", "NCHW")), flush=True)

    mod = build_fused(args.batch)
    if args.sync_trace:
        run_sync_trace(mod, args.batch, args.steps)
        return
    lowered = lower_step(mod)
    text = lowered.as_text()
    print("\n== pre-optimization StableHLO (exact benched program) ==")
    stats = analyze_stablehlo(text)
    for k, v in stats.items():
        print("  %-18s %s" % (k, v))

    cost = lowered.cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else None
    if cost:
        flops = float(cost.get("flops", 0))
        print("  cost flops/step    %.3f TFLOP" % (flops / 1e12))

    if not args.on_chip:
        return
    if dev.platform == "cpu":
        print("\n--on-chip requested but no accelerator present; stopping")
        return

    print("\n== compiling donating variant on %s ==" % dev.device_kind,
          flush=True)
    lowered_d = lower_step(mod, donate=True)
    compiled = lowered_d.compile()

    try:
        mem = compiled.memory_analysis()
        for f in ("temp_size_in_bytes", "argument_size_in_bytes",
                  "output_size_in_bytes", "alias_size_in_bytes",
                  "generated_code_size_in_bytes"):
            v = getattr(mem, f, None)
            if v is not None:
                print("  %-28s %.1f MB" % (f, v / 1e6))
    except Exception as e:  # PJRT plugins vary
        print("  memory_analysis unavailable: %s" % e)

    try:
        aliases = compiled.input_output_aliases()
        print("  input_output_aliases: %d entries" % len(aliases))
    except Exception:
        # fall back to HLO text marker
        txt = compiled.as_text()
        n = txt.count("alias")
        print("  compiled-HLO alias mentions: %d" % n)

    try:
        txt = compiled.as_text()
        post = collections.Counter(re.findall(r"^\s*\S+ = \S+? (\w+)\(",
                                              txt, re.M))
        print("  post-opt op counts (top 15):")
        for op, n in post.most_common(15):
            print("    %-22s %d" % (op, n))
        print("    transpose=%d convert=%d fusion=%d copy=%d"
              % (post.get("transpose", 0), post.get("convert", 0),
                 post.get("fusion", 0), post.get("copy", 0)))
    except Exception as e:
        print("  compiled HLO text unavailable: %s" % e)


if __name__ == "__main__":
    main()
