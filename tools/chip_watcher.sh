#!/bin/bash
# Unattended chip watcher: probe the TPU tunnel on a loop; the moment a
# window opens, run the full on-chip perf session (tools/onchip_session.sh)
# without waiting for a human. Round-4 lesson: chip minutes are the scarcest
# resource — the measurement script must already be running when the window
# opens, not written afterwards.
#
#   nohup bash tools/chip_watcher.sh &   # logs to /tmp/chipwatch/
#
# After a successful session it keeps watching and re-runs at most once more
# per 2h in case extra phases (int8 microbench, LSTM) were added meanwhile.
set -u
cd "$(dirname "$0")/.."
WATCH=/tmp/chipwatch
mkdir -p "$WATCH"
PROBE_INTERVAL=${PROBE_INTERVAL:-600}

probe() {
  # match bench.py's probe: anything that is NOT cpu counts (the axon
  # PJRT plugin may report its own platform name rather than 'tpu')
  timeout 90 python -c "import jax; assert jax.devices()[0].platform!='cpu'" \
    >/dev/null 2>&1
}

n=0
while true; do
  n=$((n+1))
  if probe; then
    echo "$(date -u +%FT%TZ) probe $n: TUNNEL UP — starting onchip session" \
      | tee -a "$WATCH/probes.log"
    bash tools/onchip_session.sh "$WATCH/session_$(date -u +%H%M)" \
      >> "$WATCH/session.log" 2>&1
    rc=$?
    echo "$(date -u +%FT%TZ) onchip session exit=$rc" | tee -a "$WATCH/probes.log"
    # extra phases, if present, each guard their own tunnel probe
    for extra in tools/onchip_extra.sh; do
      [ -x "$extra" ] && bash "$extra" "$WATCH" >> "$WATCH/extra.log" 2>&1
    done
    touch "$WATCH/SESSION_DONE"
    # results must land INSIDE the repo: if the window opened after the
    # builder session ended, the round driver commits the working tree —
    # logs left in /tmp would be lost with the container
    mkdir -p bench_logs && cp -r "$WATCH"/. bench_logs/ 2>/dev/null
    sleep 7200
  else
    echo "$(date -u +%FT%TZ) probe $n: tunnel down" >> "$WATCH/probes.log"
    sleep "$PROBE_INTERVAL"
  fi
done
