"""Serve an int8 .mxtpu artifact on the live backend and time it against
the f32 artifact of the same model (VERDICT r4 item 5, serving half:
the reference's int8 deployment story is that calibrated int8 inference
beats the float path — contrib/quantization.py:84-205).

Builds a conv tower + classifier head at batch 64, calibrates with the
naive min/max scheme, AOT-exports BOTH precisions via jax.export, then
loads + times each artifact through the serving surface. On TPU the
int8 matmuls/convs hit the MXU integer path; the printed ratio is the
deployment-relevant number.

    python tools/serve_int8_onchip.py [--batch 64] [--iters 30] [--cpu]
"""
from __future__ import annotations

import argparse
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def build_model():
    import mxnet_tpu as mx
    data = mx.sym.Variable("data")
    net = data
    for i, (f, s) in enumerate([(32, 2), (64, 2), (128, 2)]):
        net = mx.sym.Convolution(net, kernel=(3, 3), num_filter=f,
                                 stride=(s, s), pad=(1, 1),
                                 name="conv%d" % i)
        net = mx.sym.Activation(net, act_type="relu", name="relu%d" % i)
    net = mx.sym.Pooling(net, global_pool=True, pool_type="avg",
                         kernel=(1, 1), name="gap")
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=1000, name="fc")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--iters", type=int, default=30)
    ap.add_argument("--side", type=int, default=64)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()

    import jax
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import mxnet_tpu as mx
    from mxnet_tpu.contrib import quantization as Q

    dev = jax.devices()[0]
    print("device: %s (%s)" % (dev.device_kind, dev.platform), flush=True)

    sym = build_model()
    shape = (args.batch, 3, args.side, args.side)
    rng = np.random.RandomState(0)
    shapes, _, _ = sym.infer_shape(data=shape)
    params = {n: mx.nd.array(rng.uniform(-0.15, 0.15, s).astype("f4"))
              for n, s in zip(sym.list_arguments(), shapes)
              if n not in ("data", "softmax_label")}
    X = rng.rand(*shape).astype("f4")

    it = mx.io.NDArrayIter(X, np.zeros(args.batch, "f4"),
                           batch_size=args.batch,
                           label_name="softmax_label")
    qsym, qargs, qaux = Q.quantize_model(
        sym, params, {}, calib_data=it, calib_mode="naive",
        num_calib_examples=args.batch)

    tmp = tempfile.mkdtemp()
    f32_art = os.path.join(tmp, "f32.mxtpu")
    int8_art = os.path.join(tmp, "int8.mxtpu")
    mx.serving.export_compiled(sym, params, {}, {"data": shape}, f32_art)
    mx.serving.export_compiled(qsym, qargs, qaux, {"data": shape},
                               int8_art)

    def bench(path):
        cm = mx.serving.CompiledModel.load(path)
        out = cm(X)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(args.iters):
            out = cm(X)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / args.iters
        return dt, np.asarray(out[0])

    t_f32, y_f32 = bench(f32_art)
    t_int8, y_int8 = bench(int8_art)
    err = float(np.abs(y_f32 - y_int8).max())
    print("f32  artifact: %.3f ms/batch  (%.1f img/s)"
          % (t_f32 * 1e3, args.batch / t_f32), flush=True)
    print("int8 artifact: %.3f ms/batch  (%.1f img/s)"
          % (t_int8 * 1e3, args.batch / t_int8), flush=True)
    print("int8/f32 serving speedup: %.2fx   max |err| %.4f"
          % (t_f32 / t_int8, err))


if __name__ == "__main__":
    main()
