"""Gluon LSTM training throughput — the BASELINE.json "Gluon LSTM
tokens/sec" metric (reference analog: example/gluon/word_language_model
timed per-epoch; fused kernel src/operator/cudnn_rnn-inl.h:43 — here the
fused RNN is a lax.scan over the MXU-batched gate matmuls).

Drives the word-language-model shape through the PRODUCT path: gluon
Embedding -> LSTM -> Dense, autograd, hybridize, fused Trainer update.
tokens/sec = batch * seq_len * steps / wall.

One JSON line:
{"metric": "gluon_lstm_tokens_per_sec", "value": ..., ...}
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def measure(batch=32, seq_len=35, hidden=200, vocab=10000, layers=2,
            steps=10, ctx=None):
    import numpy as np
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, autograd

    ctx = ctx or (mx.tpu() if jax.devices()[0].platform != "cpu"
                  else mx.cpu())

    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Embedding(vocab, hidden))
    rnn = gluon.rnn.LSTM(hidden, num_layers=layers, layout="NTC")
    net.add(rnn)
    net.add(gluon.nn.Dense(vocab, flatten=False))
    net.initialize(mx.initializer.Xavier(), ctx=ctx)
    net.hybridize(static_alloc=True)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})

    rng = np.random.RandomState(0)
    data = mx.nd.array(rng.randint(0, vocab, (batch, seq_len)), ctx=ctx)
    label = mx.nd.array(rng.randint(0, vocab, (batch, seq_len)), ctx=ctx)

    def step():
        with autograd.record():
            out = net(data)
            loss = loss_fn(out, label)
        loss.backward()
        trainer.step(batch)
        return loss

    loss = step()   # warmup + compile
    jax.block_until_ready(loss._data)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step()
    jax.block_until_ready(loss._data)
    # force a real host sync (proxy backends can under-block)
    float(np.asarray(jax.device_get(loss._data)).ravel()[0])
    dt = time.perf_counter() - t0
    toks = batch * seq_len * steps / dt
    return {
        "metric": "gluon_lstm_tokens_per_sec",
        "value": round(toks, 1),
        "unit": "tokens/s",
        "vs_baseline": None,   # reference publishes epoch times, not tok/s
        "batch": batch, "seq_len": seq_len, "hidden": hidden,
        "vocab": vocab, "layers": layers,
        "step_ms": round(dt / steps * 1e3, 2),
        "device": jax.devices()[0].device_kind,
    }


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--seq-len", type=int, default=35)
    p.add_argument("--hidden", type=int, default=200)
    p.add_argument("--vocab", type=int, default=10000)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--platform", default=None, choices=[None, "cpu"])
    args = p.parse_args()
    if args.platform == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")
    print(json.dumps(measure(args.batch, args.seq_len, args.hidden,
                             args.vocab, args.layers, args.steps)))


if __name__ == "__main__":
    main()
