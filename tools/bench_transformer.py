"""GPT-style transformer LM training throughput (tokens/sec) on the
flash-attention path — the transformer counterpart of
tools/bench_lstm.py (reference analog: the word-LM benchmarks; here the
attention core is the blockwise/pallas flash kernel, so this number is
the long-context story's single-chip baseline).

Drives the PRODUCT path: the example's GPT blocks (gluon, hybridized),
autograd, fused Trainer update. tokens/sec = batch * seq_len * steps /
wall.

    python tools/bench_transformer.py [--dim 256 --layers 4 --seq 512]

One JSON line:
{"metric": "transformer_lm_tokens_per_sec", "value": ..., ...}
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples"))


def measure(batch=8, seq_len=512, dim=256, heads=8, layers=4,
            vocab=1024, steps=10, cpu=False):
    import jax
    if cpu:
        jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, autograd
    from train_transformer_lm import GPT, make_copy_batch

    ctx = mx.tpu() if jax.devices()[0].platform != "cpu" else mx.cpu()
    net = GPT(vocab, dim, heads, layers, seq_len)
    net.initialize(mx.initializer.Xavier(), ctx=ctx)
    net.hybridize(static_alloc=True)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 3e-4})

    rng = np.random.RandomState(0)
    data_np, label_np = make_copy_batch(rng, batch, seq_len, vocab, lag=8)
    data = mx.nd.array(data_np, ctx=ctx)
    label = mx.nd.array(label_np, ctx=ctx)

    def step():
        with autograd.record():
            out = net(data)   # pos embedding is a block Parameter
            loss = loss_fn(out, label)
        loss.backward()
        trainer.step(batch)
        return loss

    def force(l):
        # forced host fetch: block_until_ready can under-block on proxy
        # backends (same guard as bench_lstm.py / bench.py)
        return float(np.asarray(jax.device_get(l._data)).ravel()[0])

    loss = step()   # warmup + compile
    force(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step()
    force(loss)
    dt = time.perf_counter() - t0
    tps = batch * seq_len * steps / dt
    return {
        "metric": "transformer_lm_tokens_per_sec",
        "value": round(tps, 1),
        "unit": "tokens/s",
        "vs_baseline": None,   # no reference transformer baseline exists
        "batch": batch, "seq_len": seq_len, "dim": dim,
        "layers": layers, "heads": heads,
        "step_ms": round(dt / steps * 1e3, 2),
        "device": jax.devices()[0].device_kind,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=512)
    ap.add_argument("--dim", type=int, default=256)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()
    print(json.dumps(measure(args.batch, args.seq, args.dim, args.heads,
                             args.layers, steps=args.steps, cpu=args.cpu)))


if __name__ == "__main__":
    main()
