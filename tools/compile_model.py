"""Freeze a checkpoint pair into a deployable AOT inference artifact
(the reference's TensorRT build step, mx.contrib.tensorrt /
trt_graph_executor.cc — here jax.export StableHLO, cross-targetable to
TPU from a CPU host).

    python tools/compile_model.py --prefix model --epoch 10 \
        --data-shape 1,3,224,224 --out model.mxtpu [--platforms tpu]
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--prefix", required=True)
    p.add_argument("--epoch", type=int, required=True)
    p.add_argument("--data-shape", required=True,
                   help="comma dims incl. batch, e.g. 1,3,224,224")
    p.add_argument("--data-name", default="data")
    p.add_argument("--out", required=True)
    p.add_argument("--dtype", default="float32")
    p.add_argument("--platforms", default=None,
                   help="comma list, e.g. tpu (default: current backend)")
    p.add_argument("--dynamic-batch", action="store_true",
                   help="export the batch dim SYMBOLIC: one artifact "
                        "serves any batch size (what mxnet_tpu.serve's "
                        "shape-bucketed engine cache wants); the "
                        "--data-shape batch value becomes a probe size")
    p.add_argument("--platform", default=None, choices=[None, "cpu"],
                   help="backend to run the EXPORT on")
    args = p.parse_args()
    if args.platform == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")

    import mxnet_tpu as mx
    sym, arg_params, aux_params = mx.model.load_checkpoint(args.prefix,
                                                           args.epoch)
    shape = tuple(int(x) for x in args.data_shape.split(","))
    plats = args.platforms.split(",") if args.platforms else None
    meta = mx.serving.export_compiled(
        sym, arg_params, aux_params, {args.data_name: shape}, args.out,
        dtype=args.dtype, platforms=plats,
        dynamic_batch=args.dynamic_batch)
    print(json.dumps({"artifact": args.out,
                      "bytes": os.path.getsize(args.out), **meta}))


if __name__ == "__main__":
    main()
