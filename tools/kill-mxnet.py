#!/usr/bin/env python
"""Kill stray training processes on every host in a hostfile.

Parity: /root/reference/tools/kill-mxnet.py — cluster cleanup after a
crashed/hung distributed run. Same CLI: hostfile (one host per line,
``host:port`` accepted), the unix user whose processes to kill, and a
program-name pattern. Hosts are reached over ssh exactly like
tools/launch.py's ssh mode launches them; the local machine is swept last.

Usage: python tools/kill-mxnet.py <hostfile> <user> <prog>
"""
import os
import subprocess
import sys


def kill_command(user, prog_name):
    import shlex
    # pgrep then filter out our own pid ($$ is the shell running the
    # sweep): a plain pkill -f would match this script's own command
    # line (which contains the prog pattern) and SIGKILL it mid-run
    return ("for p in $(pgrep -u %s -f %s); do "
            "[ \"$p\" != \"$$\" ] && [ \"$p\" != \"%d\" ] && "
            "[ \"$p\" != \"%d\" ] && kill -9 $p; "
            "done; true" % (shlex.quote(user), shlex.quote(prog_name),
                            os.getpid(), os.getppid()))


def main():
    if len(sys.argv) != 4:
        print("usage: %s <hostfile> <user> <prog>" % sys.argv[0])
        sys.exit(1)
    host_file, user, prog_name = sys.argv[1:4]
    cmd = kill_command(user, prog_name)
    print(cmd)

    procs = []
    with open(host_file) as f:
        for host in f:
            host = host.strip()
            if not host:
                continue
            if ":" in host:
                host = host[:host.index(":")]
            print(host)
            procs.append(subprocess.Popen(
                ["ssh", "-oStrictHostKeyChecking=no", host, cmd],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE))
    for p in procs:
        p.wait()
    os.system(cmd)
    print("Done killing")


if __name__ == "__main__":
    main()
