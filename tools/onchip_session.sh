#!/bin/bash
# One-shot on-chip perf session: run the moment the TPU tunnel comes back.
# Orders the work so the most valuable numbers land first (each phase
# logs incrementally; a mid-session tunnel loss still leaves results).
#
#   bash tools/onchip_session.sh [logdir]
#
# Phase 1  microbench_convs  — are the conv kernels themselves at MXU
#                              efficiency? (small programs, fast compiles)
# Phase 2  perf_experiments  — step128 vs scan128xK: how much of the
#                              57.5ms step is per-dispatch tunnel latency?
# Phase 3  bench.py BENCH_K  — refresh BENCH_LAST_TPU.json with the
#                              grouped-dispatch fields for the round record.
# All phases share the persistent compile cache (on by default), so a
# retry after a tunnel drop skips straight past finished compiles.
set -u
cd "$(dirname "$0")/.."
LOG=${1:-/tmp/onchip}
mkdir -p "$LOG"

probe() {
  timeout 90 python -c "import jax; print(jax.devices())" >/dev/null 2>&1
}

echo "[onchip] probing tunnel..."
if ! probe; then
  echo "[onchip] tunnel down — aborting (rerun when it returns)"
  exit 2
fi

echo "[onchip] phase 1: conv microbench"
timeout 1800 python -u tools/microbench_convs.py --iters 50 \
  2>&1 | tee "$LOG/microbench.log" | grep -v -E "WARN|axon_"

echo "[onchip] phase 2: dispatch experiments"
timeout 3000 python -u tools/perf_experiments.py --steps 30 \
  --cases step128,scan128x10,scan128x30 \
  2>&1 | tee "$LOG/experiments.log" | grep -v -E "WARN|axon_"

echo "[onchip] phase 3: bench refresh (grouped dispatch K=30)"
BENCH_K=30 timeout 3600 python -u bench.py \
  2>&1 | tee "$LOG/bench.log" | tail -5

echo "[onchip] done — logs in $LOG"
