#!/bin/bash
# One-shot on-chip perf session: run the moment the TPU tunnel comes back.
# Orders the work so the most valuable numbers land first (each phase
# logs incrementally; a mid-session tunnel loss still leaves results).
#
#   bash tools/onchip_session.sh [logdir]
#
# Phase 1  microbench_convs  — are the conv kernels themselves at MXU
#                              efficiency? (small programs, fast compiles)
# Phase 2  perf_experiments  — step128 vs scan128xK: how much of the
#                              57.5ms step is per-dispatch tunnel latency?
# Phase 3  bench.py BENCH_K  — refresh BENCH_LAST_TPU.json with the
#                              grouped-dispatch fields for the round record.
# All phases share the persistent compile cache (on by default), so a
# retry after a tunnel drop skips straight past finished compiles.
set -u
cd "$(dirname "$0")/.."
LOG=${1:-/tmp/onchip}
mkdir -p "$LOG"

probe() {
  timeout 90 python -c "import jax; print(jax.devices())" >/dev/null 2>&1
}

echo "[onchip] probing tunnel..."
if ! probe; then
  echo "[onchip] tunnel down — aborting (rerun when it returns)"
  exit 2
fi

echo "[onchip] phase 0: 2-minute quick numbers (survives a tiny window)"
timeout 240 python -u - <<'EOF' 2>&1 | tee "$LOG/quick.log" | grep -v -E "WARN|axon_"
import time, json
import numpy as np
import jax, jax.numpy as jnp
dev = jax.devices()[0]
print("device:", dev.device_kind, flush=True)
# one big bf16 matmul: MXU sanity + per-dispatch latency estimate
a = jnp.asarray(np.random.rand(4096, 4096), jnp.bfloat16)
f = jax.jit(lambda a: a @ a)
out = f(a); jax.block_until_ready(out)
t0 = time.perf_counter()
for _ in range(20):
    out = f(out)
float(np.asarray(jax.device_get(out))[0, 0])  # forced fetch
dt = (time.perf_counter() - t0) / 20
tflops = 2 * 4096**3 / dt / 1e12
t1 = time.perf_counter()
for _ in range(10):
    float(np.asarray(jax.device_get(f(a)))[0, 0])  # sync every step
sync = (time.perf_counter() - t1) / 10
print(json.dumps({"quick_matmul_tflops": round(tflops, 1),
                  "pipelined_ms": round(dt * 1e3, 3),
                  "sync_roundtrip_ms": round(sync * 1e3, 3),
                  "device": dev.device_kind}), flush=True)
EOF

echo "[onchip] phase 1: conv microbench"
timeout 1800 python -u tools/microbench_convs.py --iters 50 \
  2>&1 | tee "$LOG/microbench.log" | grep -v -E "WARN|axon_"

echo "[onchip] phase 2: dispatch experiments"
timeout 3000 python -u tools/perf_experiments.py --steps 30 \
  --cases step128,scan128x10,scan128x30 \
  2>&1 | tee "$LOG/experiments.log" | grep -v -E "WARN|axon_"

echo "[onchip] phase 3: bench refresh (grouped dispatch K=30)"
BENCH_K=30 timeout 3600 python -u bench.py \
  2>&1 | tee "$LOG/bench.log" | tail -5

echo "[onchip] done — logs in $LOG"
