#!/usr/bin/env python
"""Chip-free elastic-training drill: inject a kill, survive it, prove
bitwise-identical recovery.

Runs the same 2-process dist_sync training job twice through
tools/launch.py on CPU:

1. baseline     — uninterrupted run, final params dumped;
2. kill+resume  — ``MXNET_FAULT_INJECT=kill@step=N:rank=0`` SIGKILLs
   rank 0 mid-training; the launcher's supervised restart brings the
   group back up with ``MXNET_RESUME_DIR`` set, training resumes from
   the newest common checkpoint and finishes.

The drill PASSes iff the killed-and-resumed run's final parameters are
BITWISE identical to the baseline's.  Exit code 0 on PASS, 1 on FAIL —
suitable for a nightly cron next to bench.py.

Usage::

    python tools/fault_drill.py [--kill-step N] [-n WORKERS] [--keep]
"""
import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LAUNCH = os.path.join(ROOT, "tools", "launch.py")
WORKER = os.path.join(ROOT, "tests", "fault_resume_worker.py")


def _run(tag, dump, extra_args, extra_env, verbose):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)       # workers pin CPU themselves
    env.pop("MXNET_FAULT_INJECT", None)
    env["FAULT_TRAIN_DUMP"] = dump
    env.update(extra_env)
    cmd = [sys.executable, LAUNCH] + extra_args + [sys.executable, WORKER]
    print("fault_drill: [%s] %s" % (tag, " ".join(cmd)))
    r = subprocess.run(cmd, capture_output=True, text=True, timeout=900,
                       env=env, cwd=ROOT)
    if verbose or r.returncode != 0:
        sys.stdout.write(r.stdout[-8000:])
        sys.stderr.write(r.stderr[-4000:])
    return r


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("-n", "--num-workers", type=int, default=2)
    ap.add_argument("--kill-step", type=int, default=3,
                    help="global step at which rank 0 is SIGKILLed")
    ap.add_argument("--keep", action="store_true",
                    help="keep the scratch directory for forensics")
    ap.add_argument("-v", "--verbose", action="store_true",
                    help="stream worker output even on success")
    args = ap.parse_args(argv)

    work = tempfile.mkdtemp(prefix="mxtpu_fault_drill_")
    base_dump = os.path.join(work, "baseline.npz")
    kill_dump = os.path.join(work, "killed.npz")
    ckpt_dir = os.path.join(work, "ckpt")
    n = str(args.num_workers)
    ok = False
    try:
        r = _run("baseline", base_dump,
                 ["-n", n, "--max-restarts", "0"], {}, args.verbose)
        if r.returncode != 0:
            print("fault_drill: FAIL — baseline run exited rc=%d"
                  % r.returncode)
            return 1

        telem_dir = os.path.join(work, "telemetry")
        r = _run("kill+resume", kill_dump,
                 ["-n", n, "--max-restarts", "3", "--restart-backoff",
                  "0.2", "--checkpoint-dir", ckpt_dir],
                 {"MXNET_FAULT_INJECT":
                  "kill@step=%d:rank=0" % args.kill_step,
                  "MXNET_TELEMETRY_DIR": telem_dir}, args.verbose)
        if r.returncode != 0:
            print("fault_drill: FAIL — kill+resume run exited rc=%d "
                  "(restart did not recover)" % r.returncode)
            return 1
        if "launch.py: restarting the group" not in r.stderr:
            print("fault_drill: FAIL — the injected kill never triggered "
                  "a supervised restart")
            return 1
        if "resumed from checkpoint step" not in r.stdout:
            print("fault_drill: FAIL — restarted workers did not resume "
                  "from a checkpoint")
            return 1
        import glob
        pm = glob.glob(os.path.join(telem_dir, "postmortem_rank0_*.json"))
        if not pm:
            print("fault_drill: FAIL — the killed worker left no "
                  "flight-recorder postmortem under %s" % telem_dir)
            return 1
        with open(pm[0]) as f:
            post = json.load(f)       # must be valid JSON
        if not post.get("reason", "").startswith("faultinject:"):
            print("fault_drill: FAIL — postmortem %s has unexpected "
                  "reason %r" % (pm[0], post.get("reason")))
            return 1
        print("fault_drill: postmortem ok — %s (%d step records, "
              "%d events)" % (os.path.basename(pm[0]),
                              len(post.get("steps", [])),
                              len(post.get("events", []))))

        for ln in r.stderr.splitlines():
            if ln.startswith("launch.py: summary "):
                s = json.loads(ln.split("summary ", 1)[1])
                print("fault_drill: restarts=%d dead_ranks(first)=%s"
                      % (s["restarts"], s["attempts"][0]["dead_ranks"]))

        import numpy as np
        with np.load(base_dump) as base, np.load(kill_dump) as killed:
            names = sorted(base.files)
            if names != sorted(killed.files):
                print("fault_drill: FAIL — param sets differ: %s vs %s"
                      % (names, sorted(killed.files)))
                return 1
            bad = [k for k in names
                   if not np.array_equal(base[k], killed[k])]
        if bad:
            print("fault_drill: FAIL — params diverged after kill+resume: "
                  "%s" % bad)
            return 1
        print("fault_drill: PASS — kill@step=%d survived; %d params "
              "bitwise-identical to the uninterrupted run" %
              (args.kill_step, len(names)))
        ok = True
        return 0
    finally:
        if args.keep or not ok:
            print("fault_drill: scratch kept at %s" % work)
        else:
            shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
