#!/usr/bin/env python
"""Chip-free elastic-training drill: inject a kill, survive it, prove
bitwise-identical recovery.

Runs the same 2-process dist_sync training job twice through
tools/launch.py on CPU:

1. baseline     — uninterrupted run, final params dumped;
2. kill+resume  — ``MXNET_FAULT_INJECT=kill@step=N:rank=0`` SIGKILLs
   rank 0 mid-training; the launcher's supervised restart brings the
   group back up with ``MXNET_RESUME_DIR`` set, training resumes from
   the newest common checkpoint and finishes.

The drill PASSes iff the killed-and-resumed run's final parameters are
BITWISE identical to the baseline's.  Exit code 0 on PASS, 1 on FAIL —
suitable for a nightly cron next to bench.py.

Usage::

    python tools/fault_drill.py [--kill-step N] [-n WORKERS] [--keep]

``--fleet`` runs the SERVING drill instead: a router
(``tools/route.py``) over 3 predict + 2 generate CPU replicas, one of
each armed with a deterministic mid-load kill
(``kill@serve=predict_batch:skip=K`` / ``kill@serve=decode_step:skip=K``).
PASS iff, under mixed predict+generate load, every attempted request
still completes (goodput degrades toward ~(N-1)/N, never to zero), the
killed decode sessions finish on the survivor via the router's held
cursor (migrations >= 1), both victims leave parseable flight-recorder
postmortems, and the supervised predict victim restarts clean and
re-registers.

``--router-ha`` drills the ROUTER's own death: a journaled primary
(``tools/route.py --journal``) plus a warm standby over 2 predict + 2
generate replicas; the primary is SIGKILLed mid-load. PASS iff the
standby promotes onto the same address from the write-ahead journal,
all 10 in-flight generate sessions finish with token tails BITWISE
identical to an uninterrupted reference run, zero in-flight predicts
are dropped (clients ride the failover with backoff retries), replicas
409 a write stamped with the dead primary's fencing epoch, and a
revived old primary refuses startup against the live lease. Runs
nightly next to ``--fleet``.

``--disk-loss`` drills the primary's DISK death on top of its process
death: the standby runs with ``--replicate-from`` (its own journal
directory, fed purely over HTTP WAL replication — no shared storage),
and mid-load the primary is SIGKILLed AND its journal directory
deleted. PASS iff the standby promotes from its own replicated
segments with a bumped epoch, all 10 in-flight generate sessions
finish bitwise vs the uninterrupted reference, zero acknowledged
control ops (pre-kill ``/admin/split`` acks) are lost, and
``fleet/repl_lag_records`` is visible in the promoted router's
federated /metrics. Emits a machine-parseable
``fault_drill: [disk-loss] PASS {json}`` line.
"""
import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LAUNCH = os.path.join(ROOT, "tools", "launch.py")
ROUTE = os.path.join(ROOT, "tools", "route.py")
SERVE = os.path.join(ROOT, "tools", "serve.py")
WORKER = os.path.join(ROOT, "tests", "fault_resume_worker.py")


def _run(tag, dump, extra_args, extra_env, verbose):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)       # workers pin CPU themselves
    env.pop("MXNET_FAULT_INJECT", None)
    env["FAULT_TRAIN_DUMP"] = dump
    env.update(extra_env)
    cmd = [sys.executable, LAUNCH] + extra_args + [sys.executable, WORKER]
    print("fault_drill: [%s] %s" % (tag, " ".join(cmd)))
    r = subprocess.run(cmd, capture_output=True, text=True, timeout=900,
                       env=env, cwd=ROOT)
    if verbose or r.returncode != 0:
        sys.stdout.write(r.stdout[-8000:])
        sys.stderr.write(r.stderr[-4000:])
    return r


def _assert_mxl6_clean(subdirs):
    """Pre-flight lint gate: the modules this drill is about to fault
    must be clean under the Layer-3 concurrency/control-plane rules
    (MXL601-606, modulo the committed baseline). A drill that injects
    faults into code with a KNOWN un-triaged race or journal-ordering
    bug produces noise, not evidence — fix or baseline the finding
    first (tools/mxlint.py --concurrency)."""
    if ROOT not in sys.path:
        sys.path.insert(0, ROOT)
    from mxnet_tpu.analysis import runner as lint_runner
    res = lint_runner.run(
        list(subdirs),
        baseline_path=os.path.join(ROOT, "tools", "mxlint_baseline.json"),
        enabled=frozenset(["MXL601", "MXL602", "MXL603",
                           "MXL604", "MXL605", "MXL606"]),
        root=ROOT)
    if res.new:
        for d in res.new:
            print("fault_drill: %s" % d.format(), file=sys.stderr)
        raise SystemExit(
            "fault_drill: %d new MXL6xx finding(s) in %s — refusing to "
            "inject faults into code with un-triaged concurrency/"
            "control-plane bugs" % (len(res.new), ", ".join(subdirs)))
    print("fault_drill: MXL6xx pre-flight clean (%s: %d baselined)"
          % (", ".join(subdirs), len(res.baselined)))


def _build_fleet_artifacts(predict_path, gen_path):
    """Tiny CPU artifacts for the fleet drill: a 6->4 FC predict net and
    the standard small decoder. Returns the decoder spec (the loadgen
    needs vocab/max_prompt_len/max_context for HTTP mode)."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import serving
    from mxnet_tpu.serve import decode_model as dm

    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    rng = np.random.RandomState(7)
    shapes, _, _ = net.infer_shape(data=(2, 6))
    args = {n: mx.nd.array(rng.uniform(-0.3, 0.3, s).astype("f4"))
            for n, s in zip(net.list_arguments(), shapes)
            if n not in ("data", "softmax_label")}
    mx.serving.export_compiled(net, args, {}, {"data": (None, 6)},
                               predict_path)
    spec = dm.DecoderSpec(vocab=61, dim=32, num_heads=4, num_layers=2,
                          max_prompt_len=8, page_size=4,
                          max_pages_per_slot=8, max_slots=4, num_pages=33)
    serving.export_generate(dm.init_params(spec, seed=0), spec, gen_path)
    return spec


def _fleet_get(url, path, timeout_s=5.0):
    import urllib.request
    with urllib.request.urlopen(url.rstrip("/") + path,
                                timeout=timeout_s) as r:
        return json.loads(r.read().decode())


def _wait_ready(router_url, want, timeout_s=180.0, allow_dead=None):
    """Poll the router's /fleet until ``want`` replicas are ready."""
    import time
    deadline = time.monotonic() + timeout_s
    snap = {}
    while time.monotonic() < deadline:
        try:
            snap = _fleet_get(router_url, "/fleet")
        except Exception:
            snap = {}
        counts = snap.get("counts", {})
        if counts.get("ready", 0) >= want:
            return snap
        time.sleep(0.3)
    raise RuntimeError("fleet never reached %d ready replicas "
                       "(last counts: %s)" % (want, snap.get("counts")))


def fleet_drill(args):
    """The serving leg: router + supervised replicas, deterministic
    mid-load kills, goodput/migration/postmortem assertions."""
    import glob
    import threading
    import time

    sys.path.insert(0, ROOT)
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import serve_loadgen

    _assert_mxl6_clean(["mxnet_tpu/fleet", "mxnet_tpu/serve"])

    # skip=N: the victim ignores its first N matching fire points, so
    # the kill lands mid-phase-B by construction — phase A (45 predict
    # requests over 3 replicas, no generate traffic) cannot reach it
    PREDICT_SKIP = 35
    DECODE_SKIP = 20
    A_REQUESTS, B_REQUESTS = 45, 210
    GEN_REQUESTS = 10

    work = tempfile.mkdtemp(prefix="mxtpu_fleet_drill_")
    telem = os.path.join(work, "telemetry")
    os.makedirs(telem, exist_ok=True)
    ok = False
    router = None
    sup = None
    try:
        predict_art = os.path.join(work, "predict.mxtpu")
        gen_art = os.path.join(work, "generate.mxtpu")
        print("fault_drill: [fleet] building artifacts...")
        spec = _build_fleet_artifacts(predict_art, gen_art)

        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)
        env.pop("MXNET_FAULT_INJECT", None)
        env.pop("MXNET_TELEMETRY_DIR", None)
        env["MXNET_FLEET_HEARTBEAT_S"] = "0.3"
        env["MXNET_FLEET_HEARTBEAT_TIMEOUT_S"] = "1.5"

        router = subprocess.Popen(
            [sys.executable, ROUTE, "--port", "0", "--hop-tokens", "4",
             "--heartbeat-timeout-s", "1.5"],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True, env=env, cwd=ROOT)
        router_url = json.loads(router.stdout.readline())["url"]
        print("fault_drill: [fleet] router at %s" % router_url)

        from mxnet_tpu.fleet import ReplicaSpec, ReplicaSupervisor
        sup = ReplicaSupervisor(backoff_base=0.2, backoff_cap=1.0)

        def spec_for(rid, art, extra_env=None, max_restarts=0):
            e = dict(env)
            e.update(extra_env or {})
            argv = [sys.executable, SERVE, "--artifact", art,
                    "--port", "0", "--register", router_url,
                    "--replica-id", rid]
            if art is predict_art:
                # bucket 1 only: every request is its own dispatched
                # batch, so skip=N counts REQUESTS — the kill point
                # stays deterministic under coalescing
                argv += ["--buckets", "1"]
            return ReplicaSpec(
                rid, argv, env=e, cwd=ROOT, max_restarts=max_restarts,
                log_path=os.path.join(work, rid + ".log"))

        # predict victim restarts once (clean env), decode victim stays
        # down so the migrated sessions MUST finish on the survivor
        sup.add(spec_for("p0", predict_art, {
            "MXNET_FAULT_INJECT":
                "kill@serve=predict_batch:skip=%d" % PREDICT_SKIP,
            "MXNET_TELEMETRY_DIR": telem}, max_restarts=1))
        sup.add(spec_for("p1", predict_art))
        sup.add(spec_for("p2", predict_art))
        sup.add(spec_for("g0", gen_art, {
            "MXNET_FAULT_INJECT":
                "kill@serve=decode_step:skip=%d" % DECODE_SKIP,
            "MXNET_TELEMETRY_DIR": telem}, max_restarts=0))
        sup.add(spec_for("g1", gen_art))
        sup.start(interval_s=0.2)

        print("fault_drill: [fleet] waiting for 5 ready replicas...")
        _wait_ready(router_url, 5)

        # phase A: predict-only baseline; small enough that the armed
        # victims survive it (assert they did)
        res_a = serve_loadgen.measure(router_url, concurrency=6,
                                      requests=A_REQUESTS, retries=2,
                                      shape=(1, 6))
        snap = _fleet_get(router_url, "/fleet")
        dead = [r["id"] for r in snap["replicas"] if r["dead"]]
        if res_a["completed"] != A_REQUESTS or dead:
            print("fault_drill: FAIL — baseline phase lost requests "
                  "(%d/%d) or replicas (%s)"
                  % (res_a["completed"], A_REQUESTS, dead))
            return 1
        print("fault_drill: [fleet] baseline goodput %.1f qps over %s"
              % (res_a["goodput_qps"], res_a.get("per_replica")))

        # phase B: mixed load; both victims die mid-phase
        res_b = {}
        res_g = {}

        def predict_load():
            res_b.update(serve_loadgen.measure(
                router_url, concurrency=8, requests=B_REQUESTS,
                retries=4, shape=(1, 6)))

        def generate_load():
            res_g.update(serve_loadgen.measure_generate(
                router_url, users=3, requests=GEN_REQUESTS,
                prompt_len=4, prompt_dist="fixed", max_new=10,
                output_dist="fixed", temperature=0.7, seed=11,
                retries=4, resume_evicted=3, vocab=spec.vocab,
                max_prompt_len=spec.max_prompt_len,
                max_context=spec.max_context))

        threads = [threading.Thread(target=predict_load),
                   threading.Thread(target=generate_load)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join(600)
        print("fault_drill: [fleet] mixed phase took %.1fs"
              % (time.monotonic() - t0))

        failures = []
        if res_b.get("completed") != B_REQUESTS:
            failures.append("predict lost requests under the kill: %s"
                            % {k: res_b.get(k) for k in
                               ("attempted", "completed", "rejected",
                                "expired", "errors")})
        ratio = ((res_b.get("goodput_qps") or 0.0)
                 / max(res_a["goodput_qps"], 1e-9))
        if ratio < 0.15:
            failures.append("predict goodput collapsed: %.1f -> %.1f qps"
                            % (res_a["goodput_qps"],
                               res_b.get("goodput_qps") or 0.0))
        if len(res_b.get("per_replica") or {}) < 2:
            failures.append("predict traffic did not spread: %s"
                            % res_b.get("per_replica"))
        if res_g.get("completed") != GEN_REQUESTS:
            failures.append("generate sessions lost under the kill: %s"
                            % {k: res_g.get(k) for k in
                               ("attempted", "completed", "evicted",
                                "rejected", "errors")})
        moved = (res_g.get("migrations") or 0) \
            + (res_g.get("resumed_sessions") or 0)
        if moved < 1:
            failures.append("no decode session crossed replicas "
                            "(migrations=%s resumed=%s)"
                            % (res_g.get("migrations"),
                               res_g.get("resumed_sessions")))

        # the victims must actually have died (and left postmortems)
        snap = _fleet_get(router_url, "/fleet")
        by_id = {r["id"]: r for r in snap["replicas"]}
        if not by_id.get("g0", {}).get("dead"):
            failures.append("decode victim g0 is not dead — the "
                            "injected kill never fired")
        pms = sorted(glob.glob(os.path.join(telem,
                                            "postmortem_rank*_*.json")))
        if len(pms) < 2:
            failures.append("expected 2 victim postmortems, found %d"
                            % len(pms))
        for pm in pms:
            with open(pm) as f:
                post = json.load(f)
            if not post.get("reason", "").startswith("faultinject:"):
                failures.append("postmortem %s has unexpected reason %r"
                                % (os.path.basename(pm),
                                   post.get("reason")))

        # recovery: the supervisor restarts p0 with MXNET_FAULT_INJECT
        # cleared; it re-registers under the same id and goes ready
        try:
            _wait_ready(router_url, 4, timeout_s=120.0)
            snap = _fleet_get(router_url, "/fleet")
            p0 = {r["id"]: r for r in snap["replicas"]}.get("p0", {})
            if p0.get("dead") or not p0.get("ready"):
                failures.append("restarted p0 never re-registered ready "
                                "(%s)" % p0)
        except RuntimeError as e:
            failures.append(str(e))

        if failures:
            for f in failures:
                print("fault_drill: FAIL — %s" % f)
            return 1
        print("fault_drill: [fleet] PASS — goodput %.1f -> %.1f qps "
              "(x%.2f, 1 of 3 predict replicas killed), %d/%d decode "
              "sessions done (migrations=%d resumed=%d, post-migration "
              "%.1f tok/s), %d postmortems parsed, p0 restarted clean"
              % (res_a["goodput_qps"], res_b["goodput_qps"], ratio,
                 res_g["completed"], GEN_REQUESTS,
                 res_g.get("migrations") or 0,
                 res_g.get("resumed_sessions") or 0,
                 res_g.get("post_migration_tokens_per_s") or 0.0,
                 len(pms)))
        ok = True
        return 0
    finally:
        if sup is not None:
            sup.stop(wait_s=15.0)
        if router is not None:
            router.terminate()
            try:
                router.wait(10)
            except subprocess.TimeoutExpired:
                router.kill()
        if args.keep or not ok:
            print("fault_drill: scratch kept at %s" % work)
        else:
            shutil.rmtree(work, ignore_errors=True)


def router_ha_drill(args):
    """The router-HA leg: primary router (journaled) + warm standby +
    4 replicas. SIGKILL the primary mid-load; the standby must promote
    onto the same address, resume every in-flight generate session from
    its journaled hop cursor (bitwise-identical tokens), ride every
    in-flight predict through client-side conn retries, and fence out
    the dead primary's epoch."""
    import socket
    import threading
    import time
    import urllib.error
    import urllib.request

    sys.path.insert(0, ROOT)
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import serve_loadgen

    _assert_mxl6_clean(["mxnet_tpu/fleet", "mxnet_tpu/serve"])

    GEN_SESSIONS = 10
    PREDICT_REQUESTS = 240
    MAX_NEW, TEMP = 12, 0.7

    work = tempfile.mkdtemp(prefix="mxtpu_router_ha_drill_")
    jdir = os.path.join(work, "journal")
    os.makedirs(jdir, exist_ok=True)
    ok = False
    primary = standby = revived = None
    sup = None
    try:
        predict_art = os.path.join(work, "predict.mxtpu")
        gen_art = os.path.join(work, "generate.mxtpu")
        print("fault_drill: [router-ha] building artifacts...")
        spec = _build_fleet_artifacts(predict_art, gen_art)

        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)
        env.pop("MXNET_FAULT_INJECT", None)
        env.pop("MXNET_TELEMETRY_DIR", None)
        env["MXNET_FLEET_HEARTBEAT_S"] = "0.3"
        env["MXNET_FLEET_HEARTBEAT_TIMEOUT_S"] = "1.5"
        env["MXNET_FLEET_JOURNAL_SYNC_EVERY"] = "4"

        # both router incarnations must serve the SAME address, so pick
        # a free port up front instead of --port 0
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        router_url = "http://127.0.0.1:%d" % port

        route_ha = ["--journal", jdir, "--hop-tokens", "4",
                    "--heartbeat-timeout-s", "1.5",
                    "--lease-interval-s", "0.25",
                    "--lease-timeout-s", "1.2"]
        primary = subprocess.Popen(
            [sys.executable, ROUTE, "--port", str(port)] + route_ha,
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True, env=env, cwd=ROOT)
        banner = json.loads(primary.stdout.readline())
        old_epoch = banner["epoch"]
        print("fault_drill: [router-ha] primary at %s (epoch %d)"
              % (router_url, old_epoch))
        standby = subprocess.Popen(
            [sys.executable, ROUTE, "--standby", "--port", str(port)]
            + route_ha,
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True, env=env, cwd=ROOT)
        json.loads(standby.stdout.readline())   # standby banner

        from mxnet_tpu.fleet import ReplicaSpec, ReplicaSupervisor
        sup = ReplicaSupervisor(backoff_base=0.2, backoff_cap=1.0)

        def spec_for(rid, art):
            argv = [sys.executable, SERVE, "--artifact", art,
                    "--port", "0", "--register", router_url,
                    "--replica-id", rid]
            if art is predict_art:
                argv += ["--buckets", "1"]
            return ReplicaSpec(rid, argv, env=dict(env), cwd=ROOT,
                               max_restarts=0,
                               log_path=os.path.join(work, rid + ".log"))

        for rid, art in (("p0", predict_art), ("p1", predict_art),
                         ("g0", gen_art), ("g1", gen_art)):
            sup.add(spec_for(rid, art))
        sup.start(interval_s=0.2)
        print("fault_drill: [router-ha] waiting for 4 ready replicas...")
        _wait_ready(router_url, 4)

        # reference pass: the 10 sessions uninterrupted. Position-keyed
        # sampling makes each (prompt, seed) deterministic on any
        # replica, so these tails are what the failover run must equal.
        import numpy as np
        rng = np.random.RandomState(11)
        prompts = [rng.randint(2, spec.vocab, size=4).tolist()
                   for _ in range(GEN_SESSIONS)]
        reference = []
        for i, prompt in enumerate(prompts):
            outc, out, _, _ = serve_loadgen._http_generate_session(
                router_url, prompt, MAX_NEW, TEMP, 100 + i, None,
                retries=4, resume_evicted=5, conn_retries=2)
            if outc != "ok":
                print("fault_drill: FAIL — reference session %d did "
                      "not complete (%s)" % (i, outc))
                return 1
            reference.append(list(out["tokens"]))

        # mixed load: predict storm + the same 10 sessions; primary is
        # SIGKILLed once the phase is demonstrably mid-flight
        res_p = {}
        gen_results = [None] * GEN_SESSIONS
        next_gen = [0]
        glock = threading.Lock()
        gen_done = threading.Event()

        def predict_load():
            # waves, so predicts stay in flight across the whole phase
            # (kill, outage, promotion) instead of finishing in its
            # first few hundred milliseconds on a fast machine
            agg = {"attempted": 0, "completed": 0, "rejected": 0,
                   "expired": 0, "errors": 0, "failovers_ridden": 0}
            while True:
                r = serve_loadgen.measure(
                    router_url, concurrency=6, requests=60,
                    retries=4, conn_retries=10, shape=(1, 6))
                for k in agg:
                    agg[k] += int(r.get(k) or 0)
                if gen_done.is_set() and \
                        agg["attempted"] >= PREDICT_REQUESTS:
                    break
            res_p.update(agg)

        def generate_load():
            while True:
                with glock:
                    if next_gen[0] >= GEN_SESSIONS:
                        return
                    i = next_gen[0]
                    next_gen[0] += 1
                gen_results[i] = serve_loadgen._http_generate_session(
                    router_url, prompts[i], MAX_NEW, TEMP, 100 + i,
                    None, retries=6, resume_evicted=5, conn_retries=10)

        gen_threads = [threading.Thread(target=generate_load)
                       for _ in range(3)]
        pred_thread = threading.Thread(target=predict_load)
        t0 = time.monotonic()
        pred_thread.start()
        for t in gen_threads:
            t.start()
        # kill only once ≥4 sessions have been dispatched (the 3
        # worker threads then necessarily hold in-flight hops) — a
        # fixed sleep raced the whole load to completion before the
        # kill on fast machines
        while next_gen[0] < 4 and time.monotonic() - t0 < 60:
            time.sleep(0.01)
        primary.kill()           # SIGKILL: no drain, no final compact
        t_kill = time.monotonic()
        print("fault_drill: [router-ha] primary SIGKILLed at +%.2fs "
              "(%d sessions dispatched)" % (t_kill - t0, next_gen[0]))
        for t in gen_threads:
            t.join(600)
        gen_done.set()
        pred_thread.join(600)
        print("fault_drill: [router-ha] mixed phase took %.1fs"
              % (time.monotonic() - t0))

        failures = []
        done = sum(1 for r in gen_results
                   if r is not None and r[0] == "ok")
        bitwise = sum(1 for i, r in enumerate(gen_results)
                      if r is not None and r[0] == "ok"
                      and list(r[1]["tokens"]) == reference[i])
        if done != GEN_SESSIONS:
            failures.append("generate sessions lost across the "
                            "failover: %d/%d completed"
                            % (done, GEN_SESSIONS))
        elif bitwise != GEN_SESSIONS:
            failures.append("resumed sessions diverged: only %d/%d "
                            "bitwise-identical to the uninterrupted "
                            "reference" % (bitwise, GEN_SESSIONS))
        if not res_p or res_p.get("completed") != res_p.get("attempted") \
                or (res_p.get("attempted") or 0) < PREDICT_REQUESTS:
            failures.append("predict dropped in-flight requests: %s"
                            % {k: res_p.get(k) for k in
                               ("attempted", "completed", "rejected",
                                "expired", "errors")})
        rode = (res_p.get("failovers_ridden") or 0) + \
            sum(1 for r in gen_results if r is not None and r[3])
        if rode < 1:
            failures.append("nothing rode the failover — the kill "
                            "missed the load window")

        # the standby must have promoted with a bumped fencing epoch
        # (allow it the lease timeout + replay; the load threads may
        # have outrun it only marginally)
        snap, last_err = {}, None
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            try:
                snap = _fleet_get(router_url, "/fleet")
                if (snap.get("epoch") or 0) > old_epoch:
                    break
            except Exception as e:
                last_err = e
            time.sleep(0.25)
        if not snap:
            failures.append("no router answering after the kill: %s"
                            % last_err)
        new_epoch = snap.get("epoch")
        if not new_epoch or new_epoch <= old_epoch:
            failures.append("promoted epoch did not advance (%s -> %s)"
                            % (old_epoch, new_epoch))
        if "journal" not in snap or "replay" not in snap:
            failures.append("promoted router reports no journal/replay "
                            "stats: %s" % sorted(snap))

        # a write stamped with the dead primary's epoch must be 409'd
        # by the replicas (the revived-stale-primary proof)
        ready_predict = [r for r in snap.get("replicas", [])
                         if r.get("ready") and r.get("mode") == "predict"]
        if not ready_predict:
            failures.append("no ready predict replica to fence-test")
        else:
            body = json.dumps({
                "inputs": {"data": [[0.0] * 6]},
                "fleet_epoch": old_epoch}).encode()
            req = urllib.request.Request(
                ready_predict[0]["url"].rstrip("/") + "/v1/predict",
                data=body, headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=10.0):
                    code = 200
            except urllib.error.HTTPError as e:
                code = e.code
            if code != 409:
                failures.append("replica accepted a stale-epoch write "
                                "(HTTP %d, wanted 409)" % code)

        # a revived old primary must refuse to start while the promoted
        # router holds the lease (startup guard, exit code 2)
        revived = subprocess.Popen(
            [sys.executable, ROUTE, "--port", "0"] + route_ha,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            env=env, cwd=ROOT)
        try:
            rc = revived.wait(30)
        except subprocess.TimeoutExpired:
            revived.kill()
            rc = None
        if rc != 2:
            failures.append("revived stale primary did not refuse "
                            "startup (rc=%s, wanted 2)" % rc)

        if failures:
            for f in failures:
                print("fault_drill: FAIL — %s" % f)
            return 1
        print("fault_drill: [router-ha] PASS — %d/%d sessions bitwise "
              "across the failover, %d/%d predicts (failovers ridden: "
              "%d), epoch %s -> %s, stale write 409'd, revived primary "
              "fenced out (replay: %s)"
              % (bitwise, GEN_SESSIONS, res_p["completed"],
                 PREDICT_REQUESTS, rode, old_epoch, new_epoch,
                 snap.get("replay")))
        ok = True
        return 0
    finally:
        if sup is not None:
            sup.stop(wait_s=15.0)
        for proc in (primary, standby, revived):
            if proc is None:
                continue
            proc.terminate()
            try:
                proc.wait(10)
            except subprocess.TimeoutExpired:
                proc.kill()
        if args.keep or not ok:
            print("fault_drill: scratch kept at %s" % work)
        else:
            shutil.rmtree(work, ignore_errors=True)


def disk_loss_drill(args):
    """The primary-disk-death leg: a journaled primary plus a
    REPLICATING standby (``--replicate-from``, its own local journal
    dir — no shared storage). Mid-load the primary is SIGKILLed AND its
    journal directory deleted; the standby must promote from its own
    replicated segments with a bumped epoch, every in-flight generate
    session must finish bitwise vs an uninterrupted reference, zero
    acknowledged control ops (splits acked pre-kill) may be lost, and
    ``fleet/repl_lag_records`` must be visible in the promoted router's
    federated /metrics."""
    import socket
    import threading
    import time
    import urllib.error
    import urllib.request

    sys.path.insert(0, ROOT)
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import serve_loadgen

    _assert_mxl6_clean(["mxnet_tpu/fleet", "mxnet_tpu/serve"])

    GEN_SESSIONS = 10
    PREDICT_REQUESTS = 240
    MAX_NEW, TEMP = 12, 0.7

    work = tempfile.mkdtemp(prefix="mxtpu_disk_loss_drill_")
    jdir_primary = os.path.join(work, "journal_primary")
    jdir_standby = os.path.join(work, "journal_standby")
    os.makedirs(jdir_primary, exist_ok=True)
    ok = False
    primary = standby = None
    sup = None
    try:
        predict_art = os.path.join(work, "predict.mxtpu")
        gen_art = os.path.join(work, "generate.mxtpu")
        print("fault_drill: [disk-loss] building artifacts...")
        spec = _build_fleet_artifacts(predict_art, gen_art)

        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)
        env.pop("MXNET_FAULT_INJECT", None)
        env.pop("MXNET_TELEMETRY_DIR", None)
        env["MXNET_FLEET_HEARTBEAT_S"] = "0.3"
        env["MXNET_FLEET_HEARTBEAT_TIMEOUT_S"] = "1.5"
        env["MXNET_FLEET_JOURNAL_SYNC_EVERY"] = "4"
        env["MXNET_FLEET_STANDBY_POLL_S"] = "0.1"   # replication cadence

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        router_url = "http://127.0.0.1:%d" % port

        timing = ["--hop-tokens", "4", "--heartbeat-timeout-s", "1.5",
                  "--lease-interval-s", "0.25", "--lease-timeout-s", "1.2"]
        primary = subprocess.Popen(
            [sys.executable, ROUTE, "--port", str(port),
             "--journal", jdir_primary] + timing,
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True, env=env, cwd=ROOT)
        banner = json.loads(primary.stdout.readline())
        old_epoch = banner["epoch"]
        print("fault_drill: [disk-loss] primary at %s (epoch %d, "
              "journal %s)" % (router_url, old_epoch, jdir_primary))
        # the standby shares NOTHING with the primary: own journal dir,
        # fed purely over HTTP replication
        standby = subprocess.Popen(
            [sys.executable, ROUTE, "--standby", "--port", str(port),
             "--journal", jdir_standby,
             "--replicate-from", router_url] + timing,
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True, env=env, cwd=ROOT)
        json.loads(standby.stdout.readline())   # standby banner

        from mxnet_tpu.fleet import ReplicaSpec, ReplicaSupervisor
        sup = ReplicaSupervisor(backoff_base=0.2, backoff_cap=1.0)

        def spec_for(rid, art):
            argv = [sys.executable, SERVE, "--artifact", art,
                    "--port", "0", "--register", router_url,
                    "--replica-id", rid]
            if art is predict_art:
                argv += ["--buckets", "1"]
            return ReplicaSpec(rid, argv, env=dict(env), cwd=ROOT,
                               max_restarts=0,
                               log_path=os.path.join(work, rid + ".log"))

        for rid, art in (("p0", predict_art), ("p1", predict_art),
                         ("g0", gen_art), ("g1", gen_art)):
            sup.add(spec_for(rid, art))
        sup.start(interval_s=0.2)
        print("fault_drill: [disk-loss] waiting for 4 ready replicas...")
        snap0 = _wait_ready(router_url, 4)

        # acknowledged control ops the failover must NOT lose: pin an
        # explicit 100% split per model (acked 200 by the primary,
        # journaled sync, replicated before the kill window opens)
        acked_splits = {}
        for model, versions in sorted(
                (snap0.get("models") or {}).items()):
            version = sorted(versions)[0]
            body = json.dumps({"model": model,
                               "weights": {version: 1.0}}).encode()
            req = urllib.request.Request(
                router_url + "/admin/split", data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=10.0) as r:
                out = json.loads(r.read().decode())
            acked_splits[model] = out["split"]
        if not acked_splits:
            print("fault_drill: FAIL — no models registered to split")
            return 1
        print("fault_drill: [disk-loss] acked control ops: %s"
              % acked_splits)

        # uninterrupted reference tails (position-keyed sampling makes
        # each (prompt, seed) deterministic on any replica). This also
        # gives replication ample time to stream the acked splits.
        import numpy as np
        rng = np.random.RandomState(11)
        prompts = [rng.randint(2, spec.vocab, size=4).tolist()
                   for _ in range(GEN_SESSIONS)]
        reference = []
        for i, prompt in enumerate(prompts):
            outc, out, _, _ = serve_loadgen._http_generate_session(
                router_url, prompt, MAX_NEW, TEMP, 100 + i, None,
                retries=4, resume_evicted=5, conn_retries=2)
            if outc != "ok":
                print("fault_drill: FAIL — reference session %d did "
                      "not complete (%s)" % (i, outc))
                return 1
            reference.append(list(out["tokens"]))

        res_p = {}
        gen_results = [None] * GEN_SESSIONS
        next_gen = [0]
        glock = threading.Lock()
        gen_done = threading.Event()

        def predict_load():
            agg = {"attempted": 0, "completed": 0, "rejected": 0,
                   "expired": 0, "errors": 0, "failovers_ridden": 0}
            while True:
                r = serve_loadgen.measure(
                    router_url, concurrency=6, requests=60,
                    retries=4, conn_retries=10, shape=(1, 6))
                for k in agg:
                    agg[k] += int(r.get(k) or 0)
                if gen_done.is_set() and \
                        agg["attempted"] >= PREDICT_REQUESTS:
                    break
            res_p.update(agg)

        def generate_load():
            while True:
                with glock:
                    if next_gen[0] >= GEN_SESSIONS:
                        return
                    i = next_gen[0]
                    next_gen[0] += 1
                gen_results[i] = serve_loadgen._http_generate_session(
                    router_url, prompts[i], MAX_NEW, TEMP, 100 + i,
                    None, retries=6, resume_evicted=5, conn_retries=10)

        gen_threads = [threading.Thread(target=generate_load)
                       for _ in range(3)]
        pred_thread = threading.Thread(target=predict_load)
        t0 = time.monotonic()
        pred_thread.start()
        for t in gen_threads:
            t.start()
        while next_gen[0] < 4 and time.monotonic() - t0 < 60:
            time.sleep(0.01)
        # the disk-death moment: SIGKILL the primary AND delete its
        # journal directory — the only surviving copy of the WAL is the
        # standby's replica
        primary.kill()
        try:
            primary.wait(15)
        except subprocess.TimeoutExpired:
            pass
        shutil.rmtree(jdir_primary, ignore_errors=True)
        t_kill = time.monotonic()
        print("fault_drill: [disk-loss] primary SIGKILLed + journal "
              "deleted at +%.2fs (%d sessions dispatched)"
              % (t_kill - t0, next_gen[0]))
        for t in gen_threads:
            t.join(600)
        gen_done.set()
        pred_thread.join(600)
        print("fault_drill: [disk-loss] mixed phase took %.1fs"
              % (time.monotonic() - t0))

        failures = []
        done = sum(1 for r in gen_results
                   if r is not None and r[0] == "ok")
        bitwise = sum(1 for i, r in enumerate(gen_results)
                      if r is not None and r[0] == "ok"
                      and list(r[1]["tokens"]) == reference[i])
        if done != GEN_SESSIONS:
            failures.append("generate sessions lost across the "
                            "failover: %d/%d completed"
                            % (done, GEN_SESSIONS))
        elif bitwise != GEN_SESSIONS:
            failures.append("resumed sessions diverged: only %d/%d "
                            "bitwise-identical to the uninterrupted "
                            "reference" % (bitwise, GEN_SESSIONS))
        if not res_p or res_p.get("completed") != res_p.get("attempted") \
                or (res_p.get("attempted") or 0) < PREDICT_REQUESTS:
            failures.append("predict dropped in-flight requests: %s"
                            % {k: res_p.get(k) for k in
                               ("attempted", "completed", "rejected",
                                "expired", "errors")})

        # the standby must have promoted FROM ITS OWN REPLICA with a
        # bumped epoch (the primary's journal no longer exists)
        snap, last_err = {}, None
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            try:
                snap = _fleet_get(router_url, "/fleet")
                if (snap.get("epoch") or 0) > old_epoch:
                    break
            except Exception as e:
                last_err = e
            time.sleep(0.25)
        if not snap:
            failures.append("no router answering after the disk loss: "
                            "%s" % last_err)
        new_epoch = snap.get("epoch")
        if not new_epoch or new_epoch <= old_epoch:
            failures.append("promoted epoch did not advance (%s -> %s)"
                            % (old_epoch, new_epoch))
        if "journal" not in snap or "replay" not in snap:
            failures.append("promoted router reports no journal/replay "
                            "stats: %s" % sorted(snap))
        jstats = snap.get("journal") or {}
        if jstats.get("dir") and jdir_primary in str(jstats.get("dir")):
            failures.append("promoted router is serving from the DEAD "
                            "primary's journal dir: %s" % jstats)

        # zero acked control ops lost: every pre-kill split must be in
        # the promoted router's control plane, bit-for-bit
        got_splits = snap.get("splits") or {}
        for model, weights in acked_splits.items():
            if got_splits.get(model) != weights:
                failures.append(
                    "acked control op lost across the disk loss: "
                    "split[%s] = %s, wanted %s"
                    % (model, got_splits.get(model), weights))

        # replication observability: the promoted router's federated
        # exposition must carry the replication-lag gauge it tracked
        # while it was the pulling standby
        try:
            req = urllib.request.Request(
                router_url + "/metrics?format=prometheus",
                headers={"Accept": "text/plain"})
            with urllib.request.urlopen(req, timeout=10.0) as r:
                metrics_text = r.read().decode()
        except Exception as e:
            metrics_text = ""
            failures.append("cannot scrape federated /metrics: %s" % e)
        if "mxtpu_fleet_repl_lag_records" not in metrics_text:
            failures.append("fleet/repl_lag_records missing from the "
                            "promoted router's federated /metrics")

        # stale-epoch writes must still be fenced at the replicas
        ready_predict = [r for r in snap.get("replicas", [])
                         if r.get("ready") and r.get("mode") == "predict"]
        if not ready_predict:
            failures.append("no ready predict replica to fence-test")
        else:
            body = json.dumps({
                "inputs": {"data": [[0.0] * 6]},
                "fleet_epoch": old_epoch}).encode()
            req = urllib.request.Request(
                ready_predict[0]["url"].rstrip("/") + "/v1/predict",
                data=body, headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=10.0):
                    code = 200
            except urllib.error.HTTPError as e:
                code = e.code
            if code != 409:
                failures.append("replica accepted a stale-epoch write "
                                "(HTTP %d, wanted 409)" % code)

        if failures:
            for f in failures:
                print("fault_drill: FAIL — %s" % f)
            return 1
        result = {
            "sessions_bitwise": bitwise,
            "sessions_total": GEN_SESSIONS,
            "predicts_completed": res_p.get("completed"),
            "predicts_attempted": res_p.get("attempted"),
            "epoch_old": old_epoch,
            "epoch_new": new_epoch,
            "acked_control_ops": len(acked_splits),
            "acked_control_ops_lost": 0,
            "repl_lag_metric_visible": True,
            "stale_epoch_write_fenced": True,
            "replay": snap.get("replay"),
        }
        print("fault_drill: [disk-loss] PASS " + json.dumps(result))
        ok = True
        return 0
    finally:
        if sup is not None:
            sup.stop(wait_s=15.0)
        for proc in (primary, standby):
            if proc is None:
                continue
            proc.terminate()
            try:
                proc.wait(10)
            except subprocess.TimeoutExpired:
                proc.kill()
        if args.keep or not ok:
            print("fault_drill: scratch kept at %s" % work)
        else:
            shutil.rmtree(work, ignore_errors=True)


def autoscale_drill(args):
    """The elastic-fleet leg: a journaled router running two
    Autoscalers (predict + generate models), no replicas started by
    hand. The scalers must launch the min-replica floor themselves, a
    loadgen spike must scale predict 1->3 with p99 recovering after
    the scale-out, the load drop must drain back to 1 with zero
    dropped in-flight requests, long decode sessions must ride the
    generate scaler's drain bitwise, and the journaled decisions must
    replay into a restarted router's snapshot."""
    import threading
    import time

    sys.path.insert(0, ROOT)
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import socket

    import serve_loadgen

    _assert_mxl6_clean(["mxnet_tpu/fleet", "mxnet_tpu/serve"])

    GEN_SESSIONS = 10
    MAX_NEW, TEMP = 20, 0.7

    work = tempfile.mkdtemp(prefix="mxtpu_autoscale_drill_")
    jdir = os.path.join(work, "journal")
    logs = os.path.join(work, "logs")
    os.makedirs(jdir, exist_ok=True)
    os.makedirs(logs, exist_ok=True)
    ok = False
    router = revived = None
    try:
        predict_art = os.path.join(work, "predict.mxtpu")
        gen_art = os.path.join(work, "generate.mxtpu")
        print("fault_drill: [autoscale] building artifacts...")
        spec = _build_fleet_artifacts(predict_art, gen_art)

        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)
        env.pop("MXNET_FAULT_INJECT", None)
        env.pop("MXNET_TELEMETRY_DIR", None)
        env["MXNET_FLEET_HEARTBEAT_S"] = "0.3"
        env["MXNET_FLEET_HEARTBEAT_TIMEOUT_S"] = "2.5"
        # the CPU stand-in model finishes in microseconds, so on a
        # small drill host every replica shares the same saturated
        # core and scale-out cannot move latency. Simulated device
        # occupancy (a GIL-released sleep inside the timed dispatch
        # window) makes predict service time accelerator-like: 3
        # replicas really are 3x the capacity of 1
        env["MXNET_SERVE_SIM_BATCH_S"] = "0.04"

        # both router incarnations (failover-replay check) serve the
        # same address, so pick a free port up front
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        router_url = "http://127.0.0.1:%d" % port

        serve_argv = ("%s %s --artifact %%s --port 0 "
                      "--register {register_url} "
                      "--replica-id {replica_id} --model-name %%s"
                      % (sys.executable, SERVE))
        # watermarks sit on the perfmodel-derived load_s scale each
        # mode reports: predict load_s is queue x observed row time
        # (row time ~= the 40ms simulated batch under --buckets 1, so
        # a 16-way closed-loop spike shows ~0.6s of queue per replica,
        # ~0 when idle), generate load_s is the decode session queue's
        # retry-after (real seconds). startup_cost is small: these
        # replicas warm in seconds and the drill WANTS eager scale-out.
        predict_scaler = {
            "model": "pm", "min": 1, "max": 3,
            "high_watermark_s": 0.15, "low_watermark_s": 0.02,
            "breach_rounds": 2, "cooldown_s": 1.5,
            "startup_cost_s": 0.01, "interval_s": 0.3,
            "log_dir": logs,
            "argv": serve_argv % (predict_art, "pm") + " --buckets 1",
        }
        gen_scaler = {
            "model": "gm", "min": 1, "max": 2,
            "high_watermark_s": 0.02, "low_watermark_s": 1e-4,
            "breach_rounds": 2, "cooldown_s": 1.5,
            "startup_cost_s": 1e-4, "interval_s": 0.3,
            "log_dir": logs,
            "argv": serve_argv % (gen_art, "gm"),
        }
        route_cmd = [sys.executable, ROUTE, "--port", str(port),
                     "--journal", jdir, "--hop-tokens", "4",
                     "--heartbeat-timeout-s", "2.5",
                     "--autoscale", json.dumps(predict_scaler),
                     "--autoscale", json.dumps(gen_scaler)]
        router = subprocess.Popen(
            route_cmd, stdout=subprocess.PIPE,
            stderr=open(os.path.join(logs, "router.log"), "w"),
            text=True, env=env, cwd=ROOT)
        json.loads(router.stdout.readline())   # routing banner
        json.loads(router.stdout.readline())   # autoscale banner
        print("fault_drill: [autoscale] router at %s" % router_url)

        def predict_counts(snap):
            reps = [r for r in snap.get("replicas", [])
                    if r.get("model") == "pm" and not r.get("dead")]
            in_rot = [r for r in reps
                      if r.get("ready") and not r.get("draining")]
            return len(reps), len(in_rot)

        # the scalers must bring up the min floor on their own
        print("fault_drill: [autoscale] waiting for the min-replica "
              "floor (1 predict + 1 generate)...")
        _wait_ready(router_url, 2)
        snap = _fleet_get(router_url, "/fleet")
        if "autoscale" not in snap:
            print("fault_drill: FAIL — /fleet has no autoscale section")
            return 1

        # reference decode pass: 10 uninterrupted sessions on the
        # 1-replica fleet; position-keyed sampling makes these the
        # bitwise target for the drain phase
        import numpy as np
        rng = np.random.RandomState(23)
        prompts = [rng.randint(2, spec.vocab, size=4).tolist()
                   for _ in range(GEN_SESSIONS)]
        reference = []
        for i, prompt in enumerate(prompts):
            outc, out, _, _ = serve_loadgen._http_generate_session(
                router_url, prompt, MAX_NEW, TEMP, 100 + i, None,
                retries=4, resume_evicted=5, conn_retries=2)
            if outc != "ok":
                print("fault_drill: FAIL — reference session %d did "
                      "not complete (%s)" % (i, outc))
                return 1
            reference.append(list(out["tokens"]))

        # ---- spike: scale-out + p99 recovery -------------------------------
        # waves of the loadgen spike profile's peak load until the
        # scaler reaches 3 in-rotation predict replicas
        print("fault_drill: [autoscale] spiking predict load...")
        spike_stats = {"p99s": [], "completed": 0, "attempted": 0,
                       "errors": 0}
        spike_done = threading.Event()

        def spike_load():
            while not spike_done.is_set():
                r = serve_loadgen.measure(
                    router_url, concurrency=16, requests=160,
                    retries=4, conn_retries=6, shape=(1, 6))
                spike_stats["p99s"].append(
                    (r.get("latency_ms") or {}).get("p99") or 0.0)
                for k in ("completed", "attempted", "errors"):
                    spike_stats[k] += int(r.get(k) or 0)

        spike_thread = threading.Thread(target=spike_load)
        t0 = time.monotonic()
        spike_thread.start()
        scaled = False
        while time.monotonic() - t0 < 150.0:
            try:
                snap = _fleet_get(router_url, "/fleet")
            except Exception:
                snap = {}
            _, in_rot = predict_counts(snap)
            if in_rot >= 3:
                scaled = True
                break
            time.sleep(0.3)
        t_scaled = time.monotonic() - t0
        if not scaled:
            spike_done.set()
            spike_thread.join(120)
            print("fault_drill: FAIL — spike never scaled predict to 3 "
                  "in-rotation replicas (last: %s)"
                  % (snap.get("autoscale")))
            return 1
        print("fault_drill: [autoscale] 1->3 predict replicas at "
              "+%.1fs" % t_scaled)
        # the wave in flight at detection straddles both fleet sizes;
        # recovery p99 comes from waves that START after scale-out, at
        # the SAME 16-way offered load the 1-replica fleet peaked under
        waves_at_scale = len(spike_stats["p99s"]) + 1
        t_rec = time.monotonic()
        while (len(spike_stats["p99s"]) < waves_at_scale + 2
               and time.monotonic() - t_rec < 120.0):
            time.sleep(0.5)
        spike_done.set()
        spike_thread.join(120)
        p99_peak = max(spike_stats["p99s"][:waves_at_scale] or [0.0])
        post = spike_stats["p99s"][waves_at_scale:]
        p99_rec = min(post) if post else float("inf")
        rec = {"errors": 0}
        # deadline: the scaled-out p99 must land well under the
        # single-replica spike peak (generous for CI-class CPUs)
        deadline_ms = max(0.75 * p99_peak, 100.0)
        failures = []
        if p99_rec > deadline_ms:
            failures.append(
                "p99 did not recover after scale-out: %.1fms peak -> "
                "%.1fms (deadline %.1fms)"
                % (p99_peak, p99_rec, deadline_ms))
        # rejections under the 1-replica overload are backpressure, not
        # drops; hard errors are never acceptable
        if spike_stats["errors"] or rec.get("errors"):
            failures.append(
                "spike saw hard errors: %s + recovery %s"
                % (spike_stats, {k: rec.get(k) for k in
                                 ("attempted", "completed", "errors")}))

        # ---- decode sessions across the generate scaler ---------------------
        # the 10 long sessions overload 1 gen replica (4 slots) ->
        # scale-out to 2; as sessions finish, pressure drops -> the
        # scaler drains one replica mid-flight and its sessions
        # migrate via their cursors — bitwise
        print("fault_drill: [autoscale] decode sessions through "
              "scale-out + drain...")
        gen_results = [None] * GEN_SESSIONS
        next_gen = [0]
        glock = threading.Lock()

        def generate_load():
            while True:
                with glock:
                    if next_gen[0] >= GEN_SESSIONS:
                        return
                    i = next_gen[0]
                    next_gen[0] += 1
                gen_results[i] = serve_loadgen._http_generate_session(
                    router_url, prompts[i], MAX_NEW, TEMP, 100 + i,
                    None, retries=6, resume_evicted=5, conn_retries=6)

        gen_threads = [threading.Thread(target=generate_load)
                       for _ in range(8)]
        for t in gen_threads:
            t.start()
        for t in gen_threads:
            t.join(600)
        done = sum(1 for r in gen_results
                   if r is not None and r[0] == "ok")
        bitwise = sum(1 for i, r in enumerate(gen_results)
                      if r is not None and r[0] == "ok"
                      and list(r[1]["tokens"]) == reference[i])
        if done != GEN_SESSIONS:
            failures.append("decode sessions lost under autoscaling: "
                            "%d/%d completed" % (done, GEN_SESSIONS))
        elif bitwise != GEN_SESSIONS:
            failures.append("decode sessions diverged: only %d/%d "
                            "bitwise vs the 1-replica reference"
                            % (bitwise, GEN_SESSIONS))

        # ---- drain back to the floor ---------------------------------------
        # idle fleet: both scalers must shed down to min under a light
        # trickle that must not drop a single request
        print("fault_drill: [autoscale] waiting for drain back to "
              "1 predict replica...")
        drained = False
        trickle = {"attempted": 0, "completed": 0}
        deadline = time.monotonic() + 150.0
        while time.monotonic() < deadline:
            r = serve_loadgen.measure(router_url, concurrency=1,
                                      requests=6, retries=4,
                                      conn_retries=6, shape=(1, 6))
            trickle["attempted"] += int(r.get("attempted") or 0)
            trickle["completed"] += int(r.get("completed") or 0)
            snap = _fleet_get(router_url, "/fleet")
            total, in_rot = predict_counts(snap)
            if total == 1 and in_rot == 1:
                drained = True
                break
            time.sleep(0.5)
        if not drained:
            failures.append("fleet never drained back to 1 predict "
                            "replica (autoscale: %s)"
                            % snap.get("autoscale"))
        if trickle["completed"] != trickle["attempted"]:
            failures.append("requests dropped during the drain: %s"
                            % trickle)

        # the journal must hold the decision trail
        autoscale_snap = snap.get("autoscale") or {}
        for scaler in ("pm", "gm"):
            rec_s = autoscale_snap.get(scaler) or {}
            if not rec_s.get("last"):
                failures.append("no journaled decisions for scaler %r: "
                                "%s" % (scaler, autoscale_snap))

        # ---- failover: decisions replay from the journal --------------------
        print("fault_drill: [autoscale] restarting the router from "
              "the journal...")
        router.terminate()      # graceful: final compact + lease release
        try:
            router.wait(30)
        except subprocess.TimeoutExpired:
            router.kill()
            router.wait(10)
        revived = subprocess.Popen(
            [sys.executable, ROUTE, "--port", str(port),
             "--journal", jdir, "--force-primary"],
            stdout=subprocess.PIPE,
            stderr=open(os.path.join(logs, "router2.log"), "w"),
            text=True, env=env, cwd=ROOT)
        json.loads(revived.stdout.readline())
        replay_snap = {}
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            try:
                replay_snap = _fleet_get(router_url, "/fleet")
                break
            except Exception:
                time.sleep(0.25)
        replayed = replay_snap.get("autoscale") or {}
        for scaler in ("pm", "gm"):
            rep_s = replayed.get(scaler) or {}
            if not rep_s.get("last"):
                failures.append("scaler %r state did not replay into "
                                "the restarted router: %s"
                                % (scaler, sorted(replayed)))

        if failures:
            for f in failures:
                print("fault_drill: FAIL — %s" % f)
            return 1
        print("fault_drill: [autoscale] PASS %s" % json.dumps({
            "scale_out_s": round(t_scaled, 1),
            "p99_peak_ms": round(p99_peak, 1),
            "p99_recovered_ms": round(p99_rec, 1),
            "spike_completed": spike_stats["completed"]
                               + rec.get("completed", 0),
            "decode_bitwise": "%d/%d" % (bitwise, GEN_SESSIONS),
            "drained_to": 1,
            "decisions": {k: (v.get("last") or {}).get("action")
                          for k, v in replayed.items()},
        }))
        ok = True
        return 0
    finally:
        for proc in (router, revived):
            if proc is None:
                continue
            proc.terminate()
            try:
                proc.wait(30)
            except subprocess.TimeoutExpired:
                proc.kill()
        if args.keep or not ok:
            print("fault_drill: scratch kept at %s" % work)
        else:
            shutil.rmtree(work, ignore_errors=True)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("-n", "--num-workers", type=int, default=2)
    ap.add_argument("--kill-step", type=int, default=3,
                    help="global step at which rank 0 is SIGKILLed")
    ap.add_argument("--fleet", action="store_true",
                    help="run the serving-fleet drill (router + replica "
                         "kills) instead of the training drill")
    ap.add_argument("--router-ha", action="store_true",
                    help="run the router-HA drill: SIGKILL the primary "
                         "router mid-load, the warm standby promotes "
                         "from the journal, sessions finish bitwise")
    ap.add_argument("--disk-loss", action="store_true",
                    help="run the primary-disk-death drill: SIGKILL the "
                         "primary AND delete its journal dir mid-load; "
                         "a --replicate-from standby promotes from its "
                         "own replicated WAL, sessions finish bitwise, "
                         "acked control ops survive")
    ap.add_argument("--autoscale", action="store_true",
                    help="run the elastic-fleet drill: Autoscalers "
                         "launch the replica floor, a loadgen spike "
                         "scales predict 1->3 with p99 recovering, the "
                         "load drop drains back to 1 with zero dropped "
                         "in-flight requests, decode sessions ride the "
                         "generate drain bitwise, and the journaled "
                         "decisions replay after a router restart")
    ap.add_argument("--keep", action="store_true",
                    help="keep the scratch directory for forensics")
    ap.add_argument("-v", "--verbose", action="store_true",
                    help="stream worker output even on success")
    args = ap.parse_args(argv)

    if args.fleet:
        return fleet_drill(args)
    if args.router_ha:
        return router_ha_drill(args)
    if args.disk_loss:
        return disk_loss_drill(args)
    if args.autoscale:
        return autoscale_drill(args)

    work = tempfile.mkdtemp(prefix="mxtpu_fault_drill_")
    base_dump = os.path.join(work, "baseline.npz")
    kill_dump = os.path.join(work, "killed.npz")
    ckpt_dir = os.path.join(work, "ckpt")
    n = str(args.num_workers)
    ok = False
    try:
        r = _run("baseline", base_dump,
                 ["-n", n, "--max-restarts", "0"], {}, args.verbose)
        if r.returncode != 0:
            print("fault_drill: FAIL — baseline run exited rc=%d"
                  % r.returncode)
            return 1

        telem_dir = os.path.join(work, "telemetry")
        r = _run("kill+resume", kill_dump,
                 ["-n", n, "--max-restarts", "3", "--restart-backoff",
                  "0.2", "--checkpoint-dir", ckpt_dir],
                 {"MXNET_FAULT_INJECT":
                  "kill@step=%d:rank=0" % args.kill_step,
                  "MXNET_TELEMETRY_DIR": telem_dir}, args.verbose)
        if r.returncode != 0:
            print("fault_drill: FAIL — kill+resume run exited rc=%d "
                  "(restart did not recover)" % r.returncode)
            return 1
        if "launch.py: restarting the group" not in r.stderr:
            print("fault_drill: FAIL — the injected kill never triggered "
                  "a supervised restart")
            return 1
        if "resumed from checkpoint step" not in r.stdout:
            print("fault_drill: FAIL — restarted workers did not resume "
                  "from a checkpoint")
            return 1
        import glob
        pm = glob.glob(os.path.join(telem_dir, "postmortem_rank0_*.json"))
        if not pm:
            print("fault_drill: FAIL — the killed worker left no "
                  "flight-recorder postmortem under %s" % telem_dir)
            return 1
        with open(pm[0]) as f:
            post = json.load(f)       # must be valid JSON
        if not post.get("reason", "").startswith("faultinject:"):
            print("fault_drill: FAIL — postmortem %s has unexpected "
                  "reason %r" % (pm[0], post.get("reason")))
            return 1
        print("fault_drill: postmortem ok — %s (%d step records, "
              "%d events)" % (os.path.basename(pm[0]),
                              len(post.get("steps", [])),
                              len(post.get("events", []))))

        for ln in r.stderr.splitlines():
            if ln.startswith("launch.py: summary "):
                s = json.loads(ln.split("summary ", 1)[1])
                print("fault_drill: restarts=%d dead_ranks(first)=%s"
                      % (s["restarts"], s["attempts"][0]["dead_ranks"]))

        import numpy as np
        with np.load(base_dump) as base, np.load(kill_dump) as killed:
            names = sorted(base.files)
            if names != sorted(killed.files):
                print("fault_drill: FAIL — param sets differ: %s vs %s"
                      % (names, sorted(killed.files)))
                return 1
            bad = [k for k in names
                   if not np.array_equal(base[k], killed[k])]
        if bad:
            print("fault_drill: FAIL — params diverged after kill+resume: "
                  "%s" % bad)
            return 1
        print("fault_drill: PASS — kill@step=%d survived; %d params "
              "bitwise-identical to the uninterrupted run" %
              (args.kill_step, len(names)))
        ok = True
        return 0
    finally:
        if args.keep or not ok:
            print("fault_drill: scratch kept at %s" % work)
        else:
            shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
