"""Closed-loop load generator for the online serving runtime.

Drives an in-process :class:`mxnet_tpu.serve.Server` (or a running
``tools/serve.py`` HTTP endpoint) with N concurrent workers, each
submitting its next request as soon as the previous one completes
(optionally paced to a target aggregate QPS), and reports a latency
histogram + goodput JSON:

    python tools/serve_loadgen.py --artifact model.mxtpu \
        --concurrency 16 --requests 512 [--qps 200] [--buckets 1,8,32]
    python tools/serve_loadgen.py --url http://127.0.0.1:8080 \
        --shape 1,3,224,224 --concurrency 16 --requests 512

Importable: ``measure(target, ...)`` where ``target`` is a Server, an
artifact path, a URL, or a zero-arg callable returning the current
Server (the hook the graceful-restart soak test uses to re-point
workers at a replacement server mid-run).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_HIST_EDGES_MS = [0.5, 1, 2, 5, 10, 20, 50, 100, 200, 500, 1000, 2000,
                  5000]


def scrape_prometheus(url, timeout_s=10.0):
    """GET ``/metrics`` with ``Accept: text/plain`` (what a Prometheus
    scraper sends), run the strict exposition parser over the body, and
    return a small summary — raises if the endpoint serves anything the
    parser rejects, so load tests double as conformance checks."""
    import urllib.request
    from mxnet_tpu.telemetry import prom
    req = urllib.request.Request(url.rstrip("/") + "/metrics",
                                 headers={"Accept": "text/plain"})
    with urllib.request.urlopen(req, timeout=timeout_s) as r:
        ctype = r.headers.get("Content-Type", "")
        text = r.read().decode("utf-8")
    families = prom.parse_exposition(text)   # ValueError on bad output
    n_samples = sum(len(f["samples"]) for f in families.values())
    return {
        "content_type": ctype,
        "families": len(families),
        "samples": n_samples,
        "names": sorted(families),
    }


def _http_call(url, payload, timeout_s):
    import urllib.error
    import urllib.request
    req = urllib.request.Request(
        url.rstrip("/") + "/v1/predict",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as r:
            json.loads(r.read().decode())
            return "ok", None
    except urllib.error.HTTPError as e:
        if e.code == 429:
            return "rejected", float(e.headers.get("Retry-After", 0.05))
        if e.code == 504:
            return "expired", None
        if e.code == 503:
            return "closed", None
        return "error", None
    except Exception:
        return "error", None


def measure(target, concurrency=8, requests=256, qps=None, rows=1,
            timeout_ms=None, shape=None, retries=0, seed=0):
    """Run the closed loop; returns the result dict (see module doc).

    ``retries``: how many times a rejected (429/ServerBusy) or
    closed-server submit is retried (after the retry-after hint) before
    being counted as rejected. The graceful-restart soak sets this > 0
    with a callable ``target`` so retried requests land on the
    replacement server.
    """
    import numpy as np

    is_url = isinstance(target, str) and target.startswith("http")
    get_server = None
    if not is_url:
        from mxnet_tpu.serve import Server
        if callable(target) and not isinstance(target, Server):
            get_server = target
        else:
            if isinstance(target, str):
                target = Server(target)
            get_server = lambda: target  # noqa: E731
        meta_inputs = get_server().model.meta["inputs"]
        shapes = {i["name"]: (rows,) + tuple(i["shape"][1:])
                  for i in meta_inputs}
        dtypes = {i["name"]: i["dtype"] for i in meta_inputs}
    else:
        if shape is None:
            raise ValueError("HTTP mode needs --shape (incl. batch dim)")
        shapes = {"data": tuple(shape)}
        dtypes = {"data": "float32"}

    rng = np.random.RandomState(seed)
    feeds = [{n: rng.randn(*s).astype(dtypes[n])
              for n, s in shapes.items()} for _ in range(8)]

    counters = {"completed": 0, "rejected": 0, "expired": 0, "errors": 0}
    latencies = []
    lock = threading.Lock()
    next_idx = [0]
    pace = (concurrency / qps) if qps else 0.0   # per-worker inter-arrival

    def worker(wid):
        from mxnet_tpu.serve import (DeadlineExceeded, ServerBusy,
                                     ServerClosed)
        while True:
            with lock:
                if next_idx[0] >= requests:
                    return
                i = next_idx[0]
                next_idx[0] += 1
            feed = feeds[i % len(feeds)]
            t0 = time.monotonic()
            outcome = "error"
            for attempt in range(retries + 1):
                if is_url:
                    payload = {"inputs": {n: v.tolist()
                                          for n, v in feed.items()}}
                    if timeout_ms:
                        payload["timeout_ms"] = timeout_ms
                    outcome, retry_after = _http_call(
                        target, payload,
                        timeout_s=(timeout_ms or 30000) / 1e3 + 5)
                    if outcome == "ok":
                        break
                    if outcome in ("rejected", "closed") \
                            and attempt < retries:
                        time.sleep(retry_after or 0.05)
                        continue
                    break
                try:
                    req = get_server().submit(timeout_ms=timeout_ms,
                                              **feed)
                    budget = ((timeout_ms or 30000) / 1e3) + 5
                    req.result(timeout=budget)
                    outcome = "ok"
                    break
                except ServerBusy as e:
                    outcome = "rejected"
                    if attempt < retries:
                        time.sleep(e.retry_after)
                        continue
                    break
                except ServerClosed:
                    outcome = "closed"
                    if attempt < retries:
                        time.sleep(0.05)
                        continue
                    break
                except DeadlineExceeded:
                    outcome = "expired"
                    break
                except Exception:
                    outcome = "error"
                    break
            dt_ms = (time.monotonic() - t0) * 1e3
            with lock:
                if outcome == "ok":
                    counters["completed"] += 1
                    latencies.append(dt_ms)
                elif outcome in ("rejected", "closed"):
                    counters["rejected"] += 1
                elif outcome == "expired":
                    counters["expired"] += 1
                else:
                    counters["errors"] += 1
            if pace:
                budget = pace - (time.monotonic() - t0)
                if budget > 0:
                    time.sleep(budget)

    t_start = time.monotonic()
    threads = [threading.Thread(target=worker, args=(w,), daemon=True)
               for w in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall_s = time.monotonic() - t_start

    from mxnet_tpu.serve import percentile
    hist = [0] * (len(_HIST_EDGES_MS) + 1)
    for ms in latencies:
        for j, edge in enumerate(_HIST_EDGES_MS):
            if ms <= edge:
                hist[j] += 1
                break
        else:
            hist[-1] += 1
    out = {
        "attempted": requests,
        **counters,
        "wall_s": round(wall_s, 3),
        "goodput_qps": round(counters["completed"] / wall_s, 2)
                       if wall_s > 0 else None,
        "concurrency": concurrency,
        "target_qps": qps,
        "latency_ms": {
            "p50": percentile(latencies, 50),
            "p95": percentile(latencies, 95),
            "p99": percentile(latencies, 99),
            "mean": (sum(latencies) / len(latencies)) if latencies else None,
            "max": max(latencies) if latencies else None,
        },
        "histogram": {"edges_ms": _HIST_EDGES_MS, "counts": hist},
    }
    if not is_url and get_server is not None:
        try:
            out["server_metrics"] = get_server().metrics()
        except Exception:
            pass
    return out


def main():
    p = argparse.ArgumentParser()
    g = p.add_mutually_exclusive_group(required=True)
    g.add_argument("--artifact", help="serve in-process from this artifact")
    g.add_argument("--url", help="drive a running tools/serve.py endpoint")
    p.add_argument("--concurrency", type=int, default=8)
    p.add_argument("--requests", type=int, default=256)
    p.add_argument("--qps", type=float, default=None,
                   help="aggregate target QPS (default: unpaced)")
    p.add_argument("--rows", type=int, default=1,
                   help="rows per request (in-process mode)")
    p.add_argument("--shape", default=None,
                   help="request shape incl. batch, e.g. 1,3,224,224 "
                        "(HTTP mode)")
    p.add_argument("--timeout-ms", type=float, default=None)
    p.add_argument("--retries", type=int, default=0)
    p.add_argument("--buckets", default=None)
    p.add_argument("--platform", default=None, choices=[None, "cpu"])
    p.add_argument("--out", default=None, help="also write JSON here")
    p.add_argument("--scrape-metrics", action="store_true",
                   help="after the run, scrape the endpoint's Prometheus "
                        "/metrics exposition, assert it parses, and "
                        "embed a summary (HTTP mode only)")
    args = p.parse_args()
    if args.scrape_metrics and not args.url:
        p.error("--scrape-metrics needs --url (HTTP mode)")

    if args.platform == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")

    if args.url:
        target = args.url
        shape = tuple(int(x) for x in args.shape.split(",")) \
            if args.shape else None
    else:
        from mxnet_tpu.serve import Server
        target = Server(args.artifact, buckets=args.buckets)
        shape = None

    res = measure(target, concurrency=args.concurrency,
                  requests=args.requests, qps=args.qps, rows=args.rows,
                  timeout_ms=args.timeout_ms, shape=shape,
                  retries=args.retries)
    if not args.url:
        target.close(drain=True)
    if args.scrape_metrics:
        res["prometheus"] = scrape_prometheus(args.url)
        assert res["prometheus"]["families"] > 0, \
            "/metrics exposition parsed but held no metric families"
    line = json.dumps(res)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line)


if __name__ == "__main__":
    main()
