"""Closed-loop load generator for the online serving runtime.

Drives an in-process :class:`mxnet_tpu.serve.Server` (or a running
``tools/serve.py`` HTTP endpoint) with N concurrent workers, each
submitting its next request as soon as the previous one completes
(optionally paced to a target aggregate QPS), and reports a latency
histogram + goodput JSON:

    python tools/serve_loadgen.py --artifact model.mxtpu \
        --concurrency 16 --requests 512 [--qps 200] [--buckets 1,8,32]
    python tools/serve_loadgen.py --url http://127.0.0.1:8080 \
        --shape 1,3,224,224 --concurrency 16 --requests 512

Importable: ``measure(target, ...)`` where ``target`` is a Server, an
artifact path, a URL, or a zero-arg callable returning the current
Server (the hook the graceful-restart soak test uses to re-point
workers at a replacement server mid-run).

``--generate`` switches to the generation workload (generate-mode
artifacts): closed-loop users with per-request prompt/output lengths
drawn from fixed/uniform/longtail distributions, reporting TTFT/TPOT
percentiles and tokens/s goodput — plus, against a speculative server,
the token-weighted ``accepted_tokens_per_step`` and draft acceptance
rate under ``"speculation"``. Importable as ``measure_generate``.

``--router http://...`` drives a ``tools/route.py`` fleet front end
instead of a single replica: same closed loop, but the report adds the
per-replica request distribution (from the ``replica`` field the router
stamps on every response), migration counts, and — for ``--generate`` —
the goodput of sessions that survived a replica death or eviction
mid-decode (``post_migration_tokens_per_s``). Evictions that surface as
429-with-cursor are resubmitted from ``cursor["resume_prompt"]`` after
the Retry-After hint (``--resume-evicted`` bounds how many times), so a
killed replica costs latency, not the session.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_HIST_EDGES_MS = [0.5, 1, 2, 5, 10, 20, 50, 100, 200, 500, 1000, 2000,
                  5000]


def scrape_prometheus(url, timeout_s=10.0):
    """GET ``/metrics`` with ``Accept: text/plain`` (what a Prometheus
    scraper sends), run the strict exposition parser over the body, and
    return a small summary — raises if the endpoint serves anything the
    parser rejects, so load tests double as conformance checks."""
    import urllib.request
    from mxnet_tpu.telemetry import prom
    req = urllib.request.Request(url.rstrip("/") + "/metrics",
                                 headers={"Accept": "text/plain"})
    with urllib.request.urlopen(req, timeout=timeout_s) as r:
        ctype = r.headers.get("Content-Type", "")
        text = r.read().decode("utf-8")
    families = prom.parse_exposition(text)   # ValueError on bad output
    n_samples = sum(len(f["samples"]) for f in families.values())
    return {
        "content_type": ctype,
        "families": len(families),
        "samples": n_samples,
        "names": sorted(families),
    }


def _http_call(url, payload, timeout_s):
    import urllib.error
    import urllib.request
    req = urllib.request.Request(
        url.rstrip("/") + "/v1/predict",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as r:
            return "ok", None, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        if e.code == 429:
            return ("rejected",
                    float(e.headers.get("Retry-After", 0.05)), None)
        if e.code == 504:
            return "expired", None, None
        if e.code == 503:
            return "closed", None, None
        return "error", None, None
    except (urllib.error.URLError, ConnectionError, OSError):
        # connection-level (refused/reset/unreachable): the far end is
        # between incarnations — retryable for idempotent requests
        return "conn", None, None
    except Exception:
        return "error", None, None


def measure(target, concurrency=8, requests=256, qps=None, rows=1,
            timeout_ms=None, shape=None, retries=0, seed=0, dtype=None,
            conn_retries=0):
    """Run the closed loop; returns the result dict (see module doc).

    ``retries``: how many times a rejected (429/ServerBusy) or
    closed-server submit is retried (after the retry-after hint) before
    being counted as rejected. The graceful-restart soak sets this > 0
    with a callable ``target`` so retried requests land on the
    replacement server.

    ``dtype``: route every request to that engine family of a
    multi-dtype server ("int8" for the quantized engines); None serves
    the primary model. Local-server mode only.

    ``conn_retries``: HTTP mode — how many times a connection-level
    failure (refused/reset: the router is between incarnations during
    an HA failover) is retried with the fleet's capped jittered
    backoff before counting as an error. Predict is idempotent, so
    riding a failover is safe; the report counts requests that saw a
    connection failure and still completed as ``failovers_ridden``.
    """
    import numpy as np

    is_url = isinstance(target, str) and target.startswith("http")
    get_server = None
    if not is_url:
        from mxnet_tpu.serve import Server
        if callable(target) and not isinstance(target, Server):
            get_server = target
        else:
            if isinstance(target, str):
                target = Server(target)
            get_server = lambda: target  # noqa: E731
        meta_inputs = get_server().model.meta["inputs"]
        shapes = {i["name"]: (rows,) + tuple(i["shape"][1:])
                  for i in meta_inputs}
        dtypes = {i["name"]: i["dtype"] for i in meta_inputs}
    else:
        if shape is None:
            raise ValueError("HTTP mode needs --shape (incl. batch dim)")
        shapes = {"data": tuple(shape)}
        dtypes = {"data": "float32"}

    rng = np.random.RandomState(seed)
    feeds = [{n: rng.randn(*s).astype(dtypes[n])
              for n, s in shapes.items()} for _ in range(8)]

    counters = {"completed": 0, "rejected": 0, "expired": 0, "errors": 0}
    latencies = []
    per_replica = {}     # replica id -> completed count (router mode)
    failovers_ridden = [0]   # saw a conn failure, still completed
    lock = threading.Lock()
    next_idx = [0]
    pace = (concurrency / qps) if qps else 0.0   # per-worker inter-arrival

    def worker(wid):
        from mxnet_tpu.fleet.supervisor import backoff_delay
        from mxnet_tpu.serve import (DeadlineExceeded, ServerBusy,
                                     ServerClosed)
        while True:
            with lock:
                if next_idx[0] >= requests:
                    return
                i = next_idx[0]
                next_idx[0] += 1
            feed = feeds[i % len(feeds)]
            t0 = time.monotonic()
            outcome, body = "error", None
            rode_conn = False
            admit_attempt = conn_attempt = 0
            while is_url:
                payload = {"inputs": {n: v.tolist()
                                      for n, v in feed.items()}}
                if timeout_ms:
                    payload["timeout_ms"] = timeout_ms
                outcome, retry_after, body = _http_call(
                    target, payload,
                    timeout_s=(timeout_ms or 30000) / 1e3 + 5)
                if outcome == "ok":
                    break
                if outcome == "conn" and conn_attempt < conn_retries:
                    # router mid-failover: back off (jittered — a
                    # thundering herd on the fresh primary helps no
                    # one) and resubmit the idempotent request
                    rode_conn = True
                    time.sleep(backoff_delay(conn_attempt, base=0.25,
                                             cap=2.0))
                    conn_attempt += 1
                    continue
                if outcome in ("rejected", "closed") \
                        and admit_attempt < retries:
                    admit_attempt += 1
                    time.sleep(retry_after or 0.05)
                    continue
                break
            for attempt in range(0 if is_url else retries + 1):
                try:
                    req = get_server().submit(timeout_ms=timeout_ms,
                                              dtype=dtype, **feed)
                    budget = ((timeout_ms or 30000) / 1e3) + 5
                    req.result(timeout=budget)
                    outcome = "ok"
                    break
                except ServerBusy as e:
                    outcome = "rejected"
                    if attempt < retries:
                        time.sleep(e.retry_after)
                        continue
                    break
                except ServerClosed:
                    outcome = "closed"
                    if attempt < retries:
                        time.sleep(0.05)
                        continue
                    break
                except DeadlineExceeded:
                    outcome = "expired"
                    break
                except Exception:
                    outcome = "error"
                    break
            dt_ms = (time.monotonic() - t0) * 1e3
            with lock:
                if outcome == "ok":
                    counters["completed"] += 1
                    latencies.append(dt_ms)
                    if rode_conn:
                        failovers_ridden[0] += 1
                    rid = (body or {}).get("replica")
                    if rid:
                        per_replica[rid] = per_replica.get(rid, 0) + 1
                elif outcome in ("rejected", "closed"):
                    counters["rejected"] += 1
                elif outcome == "expired":
                    counters["expired"] += 1
                else:
                    counters["errors"] += 1
            if pace:
                budget = pace - (time.monotonic() - t0)
                if budget > 0:
                    time.sleep(budget)

    t_start = time.monotonic()
    threads = [threading.Thread(target=worker, args=(w,), daemon=True)
               for w in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall_s = time.monotonic() - t_start

    from mxnet_tpu.serve import percentile
    hist = [0] * (len(_HIST_EDGES_MS) + 1)
    for ms in latencies:
        for j, edge in enumerate(_HIST_EDGES_MS):
            if ms <= edge:
                hist[j] += 1
                break
        else:
            hist[-1] += 1
    out = {
        "attempted": requests,
        **counters,
        "wall_s": round(wall_s, 3),
        "goodput_qps": round(counters["completed"] / wall_s, 2)
                       if wall_s > 0 else None,
        "concurrency": concurrency,
        "target_qps": qps,
        "latency_ms": {
            "p50": percentile(latencies, 50),
            "p95": percentile(latencies, 95),
            "p99": percentile(latencies, 99),
            "mean": (sum(latencies) / len(latencies)) if latencies else None,
            "max": max(latencies) if latencies else None,
        },
        "histogram": {"edges_ms": _HIST_EDGES_MS, "counts": hist},
    }
    if is_url:
        out["failovers_ridden"] = failovers_ridden[0]
    if per_replica:
        out["per_replica"] = dict(sorted(per_replica.items()))
    if not is_url and get_server is not None:
        try:
            out["server_metrics"] = get_server().metrics()
        except Exception:
            pass
    return out


def _predict_callable(target, dtype=None):
    """(callable feed-dict -> first output np array, input meta) for a
    Server (routed to ``dtype`` engines), artifact path, or loaded
    CompiledModel."""
    import numpy as np
    from mxnet_tpu import serving
    from mxnet_tpu.serve import Server
    if isinstance(target, Server):
        meta = target.model.meta["inputs"]

        def call(feed):
            # generous deadline: a probe row may be the first request a
            # bucket engine sees, i.e. it pays the XLA compile
            outs = target.predict(timeout_ms=600000, dtype=dtype, **feed)
            return np.asarray(outs[0])
        return call, meta
    if isinstance(target, str):
        target = serving.load_artifact(target)
    meta = target.meta["inputs"]
    model = target

    def call(feed):
        outs = model(*[feed[s["name"]] for s in meta])
        if isinstance(outs, (list, tuple)):
            outs = outs[0]
        return np.asarray(outs)
    return call, meta


def measure_accuracy(ref_target, quant_target, feeds=None, labels=None,
                     examples=256, batch=32, seed=0):
    """Replay the same (labelled) probe set through the f32 reference
    and the int8 quantized engines and report the top-1 delta — the
    number the per-bucket accuracy budget in ``bench.py`` gates on.

    ``feeds``: list of feed dicts (each ``batch`` rows); default
    deterministic synthetic batches from ``seed``. ``labels``: int
    array over all probe rows; when absent the f32 argmax IS the label
    (agreement mode: ``top1_f32`` reads 1.0 and ``top1_delta`` is the
    f32-vs-int8 disagreement rate). ``per_class_drift`` is the per-class
    |predicted-fraction(f32) - predicted-fraction(int8)| — which classes
    the quantized model drifts toward/away from.
    """
    import numpy as np

    ref_call, meta = _predict_callable(ref_target, dtype="f32")
    q_call, _ = _predict_callable(quant_target, dtype="int8")
    if feeds is None:
        rng = np.random.RandomState(seed)
        n_batches = max(1, examples // batch)
        feeds = [{s["name"]: rng.randn(batch, *s["shape"][1:])
                  .astype(s["dtype"]) for s in meta}
                 for _ in range(n_batches)]
    ref_top1, q_top1 = [], []
    for feed in feeds:
        ref_top1.append(np.argmax(ref_call(feed), axis=-1).ravel())
        q_top1.append(np.argmax(q_call(feed), axis=-1).ravel())
    ref_top1 = np.concatenate(ref_top1)
    q_top1 = np.concatenate(q_top1)
    n = len(ref_top1)
    labelled = labels is not None
    labels = (np.asarray(labels).ravel()[:n] if labelled else ref_top1)
    acc_f = float((ref_top1 == labels).mean())
    acc_q = float((q_top1 == labels).mean())
    classes = np.unique(np.concatenate([ref_top1, q_top1, labels]))
    drift = {int(c): round(abs(float((ref_top1 == c).mean())
                               - float((q_top1 == c).mean())), 6)
             for c in classes}
    return {
        "examples": n,
        "top1_f32": round(acc_f, 6),
        "top1_int8": round(acc_q, 6),
        "top1_delta": round(acc_f - acc_q, 6),
        "agreement": round(float((ref_top1 == q_top1).mean()), 6),
        "per_class_drift": drift,
        "labelled": labelled,
    }


def _sample_lengths(rng, n, mean, dist, lo, hi):
    """Length distributions for generation workloads. ``longtail`` is
    the shape that makes continuous batching matter: mostly-short with a
    geometric tail out to ``hi`` — a static batch runs at the pace of
    its longest member, a continuous one refills the short finishers."""
    import numpy as np
    mean = max(lo, min(mean, hi))
    if dist == "fixed":
        vals = np.full(n, mean)
    elif dist == "uniform":
        vals = rng.randint(lo, hi + 1, size=n)
    else:   # longtail (geometric)
        p_geo = min(0.95, 1.0 / max(1.0, mean - lo + 1))
        vals = lo + rng.geometric(p=p_geo, size=n) - 1
    return np.clip(vals, lo, hi).astype(int)


def _http_generate(url, payload, timeout_s):
    import urllib.error
    import urllib.request
    req = urllib.request.Request(
        url.rstrip("/") + "/v1/generate",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as r:
            return "ok", json.loads(r.read().decode()), None
    except urllib.error.HTTPError as e:
        retry = float(e.headers.get("Retry-After", 0.05))
        if e.code == 429:
            try:
                body = json.loads(e.read().decode())
            except Exception:
                body = {}
            # an eviction carries partial tokens + a resumable cursor;
            # a plain 429 is an admission reject
            kind = "evicted" if body.get("cursor") else "rejected"
            return kind, body, retry
        if e.code == 504:
            return "expired", None, None
        if e.code == 503:
            return "closed", None, retry
        return "error", None, None
    except (urllib.error.URLError, ConnectionError, OSError):
        return "conn", None, None
    except Exception:
        return "error", None, None


def _http_generate_session(url, prompt, budget, temperature, seed,
                           timeout_ms, retries, resume_evicted,
                           conn_retries=0):
    """One logical generation over HTTP: admission-reject retries plus
    bounded cursor resubmission. An eviction's partial tokens are
    banked and the session continues from ``cursor["resume_prompt"]``
    (same seed — position-keyed sampling keeps the tail identical to an
    uninterrupted run). A connection-level failure (the router is
    between incarnations mid-failover) is retried with the fleet's
    jittered backoff; the resubmitted request hashes to the same
    session id on the promoted router, which adopts the journaled hop
    cursor — so the tokens still come back bitwise-identical. Returns
    (outcome, merged out dict, resumes, rode_failover)."""
    tokens = []
    cur_prompt = list(prompt)
    remaining = int(budget)
    resumes = rejects = conn_attempt = 0
    rode = False
    out = None
    while True:
        if remaining <= 0:
            return "ok", {"tokens": tokens, "finish_reason": "length"}, \
                resumes, rode
        payload = {"prompt": cur_prompt, "max_new_tokens": remaining,
                   "temperature": temperature, "seed": seed}
        if timeout_ms:
            payload["timeout_ms"] = timeout_ms
        outcome, out, retry_after = _http_generate(
            url, payload, timeout_s=(timeout_ms or 60000) / 1e3 + 30)
        if outcome == "ok":
            out = dict(out or {})
            out["tokens"] = tokens + list(out.get("tokens") or [])
            return "ok", out, resumes, rode
        if outcome == "conn":
            if conn_attempt >= conn_retries:
                return "error", out, resumes, rode
            from mxnet_tpu.fleet.supervisor import backoff_delay
            rode = True
            time.sleep(backoff_delay(conn_attempt, base=0.25, cap=2.0))
            conn_attempt += 1
            continue
        if outcome == "evicted":
            got = list((out or {}).get("tokens") or [])
            cursor = (out or {}).get("cursor") or {}
            if resumes >= resume_evicted \
                    or not cursor.get("resume_prompt"):
                out = dict(out or {})
                out["tokens"] = tokens + got
                return "evicted", out, resumes, rode
            tokens += got
            cur_prompt = list(cursor["resume_prompt"])
            remaining = int(cursor.get("remaining_tokens")
                            or (budget - len(tokens)))
            resumes += 1
            time.sleep(min(retry_after or 0.05, 0.5))
            continue
        if outcome in ("rejected", "closed") and rejects < retries:
            rejects += 1
            time.sleep(retry_after or 0.05)
            continue
        return outcome, out, resumes, rode


def measure_generate(target, users=4, requests=64, prompt_len=8,
                     prompt_dist="longtail", max_new=16,
                     output_dist="longtail", temperature=0.0,
                     timeout_ms=None, retries=0, seed=0, vocab=None,
                     max_prompt_len=None, max_context=None,
                     resume_evicted=0, conn_retries=0):
    """Closed-loop generation benchmark: ``users`` workers, each
    submitting its next prompt the moment the previous completion lands.
    Prompt/output lengths are drawn per-request from the configured
    distributions. Reports TTFT/TPOT percentiles and tokens/s goodput
    (completed requests' tokens over wall time) — the serving numbers
    that actually matter for autoregressive decode.

    ``target``: a generate-mode Server, a GenerateSession, an artifact
    path, or an ``http://`` URL of a running generate server or fleet
    router. HTTP mode needs ``vocab``/``max_prompt_len``/``max_context``
    since the spec is not visible through the wire.

    ``resume_evicted``: HTTP mode — how many times a 429-with-cursor
    (an eviction, or a router that ran out of replicas mid-session) is
    resubmitted from ``cursor["resume_prompt"]`` after the Retry-After
    hint. Banked partial tokens count toward the session either way;
    with resumes the session completes across replicas instead of
    surfacing the eviction to the caller.

    ``conn_retries``: HTTP mode — connection-level retry budget per
    request (router failover riding; see :func:`measure`). Sessions
    that saw a connection failure and still completed are reported as
    ``failovers_ridden``.
    """
    import numpy as np

    is_url = isinstance(target, str) and target.startswith("http")
    session = None
    if not is_url:
        from mxnet_tpu.serve import GenerateSession, Server
        if isinstance(target, str):
            target = Server(target)
        if isinstance(target, Server):
            session = target.session
            if session is None:
                raise ValueError("measure_generate needs a generate-mode "
                                 "server (a format_version 3/5 artifact)")
        elif isinstance(target, GenerateSession):
            session = target
        else:
            raise ValueError("unsupported generate target %r" % (target,))
        spec = session.spec
        vocab = spec.vocab
        max_prompt_len = spec.max_prompt_len
        max_context = spec.max_context
    else:
        if not (vocab and max_prompt_len and max_context):
            raise ValueError("HTTP generate mode needs --vocab, "
                             "--max-prompt-len and --max-context")

    rng = np.random.RandomState(seed)
    plens = _sample_lengths(rng, requests, prompt_len, prompt_dist,
                            1, max_prompt_len)
    olens = _sample_lengths(rng, requests, max_new, output_dist, 1,
                            max(1, max_context - int(plens.max())))
    olens = np.minimum(olens, max_context - plens)
    prompts = [rng.randint(2, max(3, vocab), size=int(plens[i])).tolist()
               for i in range(requests)]

    counters = {"completed": 0, "evicted": 0, "rejected": 0,
                "expired": 0, "errors": 0}
    ttfts, tpots, latencies = [], [], []
    tokens_ok = [0]
    tokens_partial = [0]
    per_replica = {}          # replica -> completions it finished
    spec_agg = {"w": 0, "atps": 0.0, "rate": 0.0}   # token-weighted
    migrations_total = [0]    # router-reported mid-session owner moves
    resumed_sessions = [0]    # sessions completed via cursor resubmit
    failovers_ridden = [0]    # sessions that rode a router failover
    migrated = {"tokens": 0, "wall_s": 0.0}   # post-migration goodput
    lock = threading.Lock()
    next_idx = [0]

    def worker(wid):
        from mxnet_tpu.serve import (DeadlineExceeded, Evicted,
                                     ServerBusy, ServerClosed)
        while True:
            with lock:
                if next_idx[0] >= requests:
                    return
                i = next_idx[0]
                next_idx[0] += 1
            t0 = time.monotonic()
            outcome, out, resumes, rode = "error", None, 0, False
            for attempt in range(retries + 1):
                if is_url:
                    outcome, out, resumes, rode = \
                        _http_generate_session(
                            target, prompts[i], int(olens[i]),
                            temperature, int(seed + i), timeout_ms,
                            retries, resume_evicted,
                            conn_retries=conn_retries)
                    break
                try:
                    out = session.generate(
                        prompts[i], max_new_tokens=int(olens[i]),
                        temperature=temperature, seed=int(seed + i),
                        timeout_ms=timeout_ms)
                    outcome = "ok"
                    break
                except Evicted as e:
                    outcome, out = "evicted", {"tokens": e.tokens}
                    break
                except ServerBusy as e:
                    outcome = "rejected"
                    if attempt < retries:
                        time.sleep(e.retry_after)
                        continue
                    break
                except (ServerClosed,) :
                    outcome = "closed"
                    if attempt < retries:
                        time.sleep(0.05)
                        continue
                    break
                except DeadlineExceeded:
                    outcome = "expired"
                    break
                except Exception:
                    outcome = "error"
                    break
            dt_ms = (time.monotonic() - t0) * 1e3
            with lock:
                if outcome == "ok":
                    counters["completed"] += 1
                    latencies.append(dt_ms)
                    ntok = len(out.get("tokens", []))
                    tokens_ok[0] += ntok
                    if out.get("ttft_ms") is not None:
                        ttfts.append(out["ttft_ms"])
                    if out.get("tpot_ms") is not None:
                        tpots.append(out["tpot_ms"])
                    rid = out.get("replica")
                    if rid:
                        per_replica[rid] = per_replica.get(rid, 0) + 1
                    atps = out.get("accepted_tokens_per_step")
                    if atps is not None and ntok:
                        # speculative servers stamp per-request draft
                        # stats on the response; aggregate them weighted
                        # by tokens so long completions dominate
                        spec_agg["w"] += ntok
                        spec_agg["atps"] += float(atps) * ntok
                        spec_agg["rate"] += float(
                            out.get("draft_acceptance_rate") or 0.0) * ntok
                    mig = int(out.get("migrations") or 0)
                    migrations_total[0] += mig
                    if resumes:
                        resumed_sessions[0] += 1
                    if rode:
                        failovers_ridden[0] += 1
                    if mig or resumes:
                        # sessions that crossed replicas: their goodput
                        # is the ~1/N-degradation evidence
                        migrated["tokens"] += ntok
                        migrated["wall_s"] += dt_ms / 1e3
                elif outcome == "evicted":
                    counters["evicted"] += 1
                    tokens_partial[0] += len((out or {}).get("tokens", []))
                elif outcome in ("rejected", "closed"):
                    counters["rejected"] += 1
                elif outcome == "expired":
                    counters["expired"] += 1
                else:
                    counters["errors"] += 1

    t_start = time.monotonic()
    threads = [threading.Thread(target=worker, args=(w,), daemon=True)
               for w in range(users)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall_s = time.monotonic() - t_start

    from mxnet_tpu.serve import percentile

    def _pct(xs):
        return {"p50": percentile(xs, 50), "p95": percentile(xs, 95),
                "p99": percentile(xs, 99),
                "mean": (sum(xs) / len(xs)) if xs else None}

    out = {
        "attempted": requests,
        **counters,
        "users": users,
        "wall_s": round(wall_s, 3),
        "tokens_completed": tokens_ok[0],
        "tokens_evicted_partial": tokens_partial[0],
        "tokens_per_s_goodput": round(tokens_ok[0] / wall_s, 2)
                                if wall_s > 0 else None,
        "prompt_len": {"dist": prompt_dist, "mean": float(plens.mean()),
                       "max": int(plens.max())},
        "output_len": {"dist": output_dist, "mean": float(olens.mean()),
                       "max": int(olens.max())},
        "ttft_ms": _pct(ttfts),
        "tpot_ms": _pct(tpots),
        "latency_ms": _pct(latencies),
    }
    if spec_agg["w"]:
        out["speculation"] = {
            "accepted_tokens_per_step": round(
                spec_agg["atps"] / spec_agg["w"], 4),
            "draft_acceptance_rate": round(
                spec_agg["rate"] / spec_agg["w"], 4),
        }
    if is_url:
        out["migrations"] = migrations_total[0]
        out["resumed_sessions"] = resumed_sessions[0]
        out["failovers_ridden"] = failovers_ridden[0]
        out["post_migration_tokens_per_s"] = (
            round(migrated["tokens"] / migrated["wall_s"], 2)
            if migrated["wall_s"] > 0 else None)
    if per_replica:
        out["per_replica"] = dict(sorted(per_replica.items()))
    if session is not None:
        try:
            out["server_metrics"] = session.metrics()
        except Exception:
            pass
    return out


def _http_recommend(url, payload, timeout_s):
    import urllib.error
    import urllib.request
    req = urllib.request.Request(
        url.rstrip("/") + "/v1/recommend",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as r:
            return "ok", None, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        if e.code == 429:
            return ("rejected",
                    float(e.headers.get("Retry-After", 0.05)), None)
        if e.code == 504:
            return "expired", None, None
        if e.code == 503:
            return "closed", None, None
        return "error", None, None
    except (urllib.error.URLError, ConnectionError, OSError):
        return "conn", None, None
    except Exception:
        return "error", None, None


#: Shaped-load profiles: (phase name, load fraction of the peak
#: --concurrency/--requests). ``spike`` is the autoscale drill's shape
#: (quiet -> slam -> quiet), ``ramp`` a capacity walk, ``diurnal`` a
#: compressed day curve.
PROFILE_PHASES = {
    "spike": [("baseline", 0.25), ("spike", 1.0), ("recovery", 0.25)],
    "ramp": [("r25", 0.25), ("r50", 0.5), ("r75", 0.75), ("r100", 1.0)],
    "diurnal": [("night", 0.2), ("morning", 0.6), ("midday", 1.0),
                ("evening", 0.6), ("late", 0.2)],
}


def measure_profile(profile, run_phase, peak_concurrency,
                    peak_requests):
    """Drive a shaped load profile: run each phase at its fraction of
    the peak concurrency/request budget via ``run_phase(concurrency,
    requests)`` (any of the measure_* closures) and report per-phase
    goodput + latency percentiles — the evidence the autoscale drill
    asserts on (did p99 recover after the scale-out?)."""
    phases = []
    for name, frac in PROFILE_PHASES[profile]:
        conc = max(1, int(round(peak_concurrency * frac)))
        reqs = max(conc, int(round(peak_requests * frac)))
        r = run_phase(conc, reqs)
        lat = r.get("latency_ms") or {}
        phases.append({
            "phase": name, "load_fraction": frac,
            "concurrency": conc, "requests": reqs,
            "goodput": (r.get("goodput_qps")
                        if r.get("goodput_qps") is not None
                        else r.get("tokens_per_s_goodput")),
            "p50_ms": lat.get("p50"), "p99_ms": lat.get("p99"),
            "completed": r.get("completed"),
            "rejected": r.get("rejected"),
            "expired": r.get("expired"),
            "errors": r.get("errors"),
            "wall_s": r.get("wall_s"),
            "detail": r,
        })
    p99s = [p["p99_ms"] for p in phases if p["p99_ms"] is not None]
    return {
        "profile": profile,
        "phases": phases,
        "peak_p99_ms": max(p99s) if p99s else None,
        "final_p99_ms": p99s[-1] if p99s else None,
        "total_completed": sum(p["completed"] or 0 for p in phases),
        "total_errors": sum(p["errors"] or 0 for p in phases),
    }


def measure_recommend(target, concurrency=8, requests=256, mean_ids=8,
                      zipf=1.3, rows=None, timeout_ms=None, retries=0,
                      seed=0, conn_retries=0):
    """Closed-loop recommend benchmark: ragged Zipf-skewed id-list
    requests (the traffic shape the hot-row cache exists for), p50/p99
    latency + goodput, and the server's cache hit rate after the run.

    ``target``: a recommend-mode Server, a format_version-6 artifact
    path, or an ``http://`` URL (replica or fleet router — router mode
    adds the per-replica request distribution). ``rows`` bounds the
    sampled ids; in-process it defaults to the engine's user-table
    rows, over HTTP it is read from ``GET /info``.
    """
    import numpy as np

    is_url = isinstance(target, str) and target.startswith("http")
    server = None
    max_ids = 64
    if not is_url:
        from mxnet_tpu.serve import Server
        if isinstance(target, str):
            target = Server(target)
        server = target
        if server.mode != "recommend":
            raise ValueError("measure_recommend needs a recommend-mode "
                             "server (a format_version-6 artifact)")
        rows = rows or server.engine.rows
        max_ids = server.engine.max_ids
    elif rows is None:
        import urllib.request
        with urllib.request.urlopen(target.rstrip("/") + "/info",
                                    timeout=10) as r:
            info = json.loads(r.read().decode())
        reco = info.get("recommend") or {}
        rows = reco.get("rows")
        max_ids = reco.get("max_ids") or max_ids
        if not rows:
            raise ValueError("target's /info has no recommend section; "
                             "pass rows= explicitly")

    rng = np.random.RandomState(seed)
    # ragged lengths (geometric around the mean) and Zipf-skewed ids:
    # the head rows take most lookups, which is what gives the hot-row
    # cache its hit rate
    lens = _sample_lengths(rng, requests, mean_ids, "longtail",
                           1, max_ids)
    id_lists = [((rng.zipf(zipf, size=int(lens[i])) - 1) % rows)
                .astype("int64").tolist() for i in range(requests)]

    counters = {"completed": 0, "rejected": 0, "expired": 0, "errors": 0}
    latencies = []
    gathers_done = [0]
    per_replica = {}
    failovers_ridden = [0]
    lock = threading.Lock()
    next_idx = [0]

    def worker(wid):
        from mxnet_tpu.fleet.supervisor import backoff_delay
        from mxnet_tpu.serve import (DeadlineExceeded, ServerBusy,
                                     ServerClosed)
        while True:
            with lock:
                if next_idx[0] >= requests:
                    return
                i = next_idx[0]
                next_idx[0] += 1
            ids = id_lists[i]
            t0 = time.monotonic()
            outcome, body = "error", None
            rode_conn = False
            admit_attempt = conn_attempt = 0
            while is_url:
                payload = {"ids": ids}
                if timeout_ms:
                    payload["timeout_ms"] = timeout_ms
                outcome, retry_after, body = _http_recommend(
                    target, payload,
                    timeout_s=(timeout_ms or 30000) / 1e3 + 5)
                if outcome == "ok":
                    break
                if outcome == "conn" and conn_attempt < conn_retries:
                    rode_conn = True
                    time.sleep(backoff_delay(conn_attempt, base=0.25,
                                             cap=2.0))
                    conn_attempt += 1
                    continue
                if outcome in ("rejected", "closed") \
                        and admit_attempt < retries:
                    admit_attempt += 1
                    time.sleep(retry_after or 0.05)
                    continue
                break
            for attempt in range(0 if is_url else retries + 1):
                try:
                    req = server.submit_recommend(ids,
                                                  timeout_ms=timeout_ms)
                    budget = ((timeout_ms or 30000) / 1e3) + 5
                    req.result(timeout=budget)
                    body = {"gathers": req.units}
                    outcome = "ok"
                    break
                except ServerBusy as e:
                    outcome = "rejected"
                    if attempt < retries:
                        time.sleep(e.retry_after)
                        continue
                    break
                except ServerClosed:
                    outcome = "closed"
                    if attempt < retries:
                        time.sleep(0.05)
                        continue
                    break
                except DeadlineExceeded:
                    outcome = "expired"
                    break
                except Exception:
                    outcome = "error"
                    break
            dt_ms = (time.monotonic() - t0) * 1e3
            with lock:
                if outcome == "ok":
                    counters["completed"] += 1
                    latencies.append(dt_ms)
                    gathers_done[0] += int((body or {}).get("gathers")
                                           or len(ids))
                    if rode_conn:
                        failovers_ridden[0] += 1
                    rid = (body or {}).get("replica")
                    if rid:
                        per_replica[rid] = per_replica.get(rid, 0) + 1
                elif outcome in ("rejected", "closed"):
                    counters["rejected"] += 1
                elif outcome == "expired":
                    counters["expired"] += 1
                else:
                    counters["errors"] += 1

    t_start = time.monotonic()
    threads = [threading.Thread(target=worker, args=(w,), daemon=True)
               for w in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall_s = time.monotonic() - t_start

    from mxnet_tpu.serve import percentile
    out = {
        "attempted": requests,
        **counters,
        "wall_s": round(wall_s, 3),
        "goodput_qps": round(counters["completed"] / wall_s, 2)
                       if wall_s > 0 else None,
        "gathers_per_s": round(gathers_done[0] / wall_s, 1)
                         if wall_s > 0 else None,
        "concurrency": concurrency,
        "ids_per_request": {"mean": float(lens.mean()),
                            "max": int(lens.max()), "zipf_a": zipf},
        "latency_ms": {
            "p50": percentile(latencies, 50),
            "p95": percentile(latencies, 95),
            "p99": percentile(latencies, 99),
            "mean": (sum(latencies) / len(latencies)) if latencies
                    else None,
            "max": max(latencies) if latencies else None,
        },
    }
    if is_url:
        out["failovers_ridden"] = failovers_ridden[0]
    if per_replica:
        out["per_replica"] = dict(sorted(per_replica.items()))
    if server is not None:
        st = server.engine.stats()
        out["cache_hit_rate"] = st["hit_rate"]
        out["embed"] = st
    elif not per_replica:
        # bare replica over HTTP: the hit rate lives in its /metrics
        try:
            import urllib.request
            with urllib.request.urlopen(
                    target.rstrip("/") + "/metrics", timeout=10) as r:
                snap = json.loads(r.read().decode())
            out["cache_hit_rate"] = (snap.get("embed") or {}).get(
                "hit_rate")
        except Exception:
            pass
    return out


def main():
    p = argparse.ArgumentParser()
    g = p.add_mutually_exclusive_group(required=True)
    g.add_argument("--artifact", help="serve in-process from this artifact")
    g.add_argument("--url", help="drive a running tools/serve.py endpoint")
    g.add_argument("--router",
                   help="drive a tools/route.py fleet front end: same "
                        "protocol as --url plus per-replica request "
                        "distribution, migration counts, and cursor "
                        "resubmission across replica deaths")
    p.add_argument("--concurrency", type=int, default=8)
    p.add_argument("--requests", type=int, default=256)
    p.add_argument("--qps", type=float, default=None,
                   help="aggregate target QPS (default: unpaced)")
    p.add_argument("--rows", type=int, default=1,
                   help="rows per request (in-process mode)")
    p.add_argument("--shape", default=None,
                   help="request shape incl. batch, e.g. 1,3,224,224 "
                        "(HTTP mode)")
    p.add_argument("--timeout-ms", type=float, default=None)
    p.add_argument("--retries", type=int, default=0)
    p.add_argument("--resume-evicted", type=int, default=None,
                   help="--generate over HTTP: max cursor resubmissions "
                        "per session after a 429-with-cursor (default 2 "
                        "in --router mode, 0 against a bare replica)")
    p.add_argument("--conn-retries", type=int, default=None,
                   help="HTTP mode: connection-refused/reset retry "
                        "budget per request with capped jittered "
                        "backoff — rides a router HA failover (default "
                        "6 in --router mode, 0 against a bare replica)")
    p.add_argument("--buckets", default=None)
    p.add_argument("--generate", action="store_true",
                   help="generation workload (generate-mode artifact / "
                        "server): closed-loop users, sampled prompt/"
                        "output lengths, TTFT/TPOT + tokens/s goodput")
    p.add_argument("--recommend", action="store_true",
                   help="recommend workload (format_version-6 artifact "
                        "/ server): ragged Zipf id-list requests, "
                        "p50/p99 + cache hit rate")
    p.add_argument("--mean-ids", type=int, default=8,
                   help="mean history length per request (--recommend)")
    p.add_argument("--zipf", type=float, default=1.3,
                   help="Zipf skew of sampled row ids (--recommend)")
    p.add_argument("--reco-rows", type=int, default=None,
                   help="user-table row bound for sampled ids "
                        "(--recommend; default: engine rows or /info)")
    p.add_argument("--prompt-len", type=int, default=8,
                   help="mean prompt length (--generate)")
    p.add_argument("--prompt-dist", default="longtail",
                   choices=["fixed", "uniform", "longtail"])
    p.add_argument("--max-new", type=int, default=16,
                   help="mean output length (--generate)")
    p.add_argument("--output-dist", default="longtail",
                   choices=["fixed", "uniform", "longtail"])
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--vocab", type=int, default=None,
                   help="HTTP --generate mode: the model's vocab size")
    p.add_argument("--max-prompt-len", type=int, default=None)
    p.add_argument("--max-context", type=int, default=None)
    p.add_argument("--accuracy-probe", action="store_true",
                   help="instead of a load run: replay a labelled probe "
                        "set through --artifact (f32) and "
                        "--quant-artifact (int8), report top-1 delta + "
                        "per-class drift")
    p.add_argument("--quant-artifact", default=None,
                   help="format_version-4 int8 artifact "
                        "(--accuracy-probe)")
    p.add_argument("--probe-npz", default=None,
                   help=".npz with 'data' (+ optional 'labels') for the "
                        "probe; default synthetic from --seed")
    p.add_argument("--probe-examples", type=int, default=256)
    p.add_argument("--probe-batch", type=int, default=32)
    p.add_argument("--profile", default=None,
                   choices=sorted(PROFILE_PHASES),
                   help="shaped load instead of one flat run: phases "
                        "at fractions of the peak --concurrency/"
                        "--requests, per-phase goodput + p50/p99 in "
                        "the report (spike = the autoscale drill's "
                        "quiet/slam/quiet shape)")
    p.add_argument("--platform", default=None, choices=[None, "cpu"])
    p.add_argument("--out", default=None, help="also write JSON here")
    p.add_argument("--scrape-metrics", action="store_true",
                   help="after the run, scrape the endpoint's Prometheus "
                        "/metrics exposition, assert it parses, and "
                        "embed a summary (HTTP mode only)")
    args = p.parse_args()
    url = args.url or args.router
    if args.scrape_metrics and not url:
        p.error("--scrape-metrics needs --url or --router (HTTP mode)")
    resume_evicted = args.resume_evicted
    if resume_evicted is None:
        resume_evicted = 2 if args.router else 0
    conn_retries = args.conn_retries
    if conn_retries is None:
        conn_retries = 6 if args.router else 0

    if args.platform == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")

    if args.accuracy_probe:
        if not (args.artifact and args.quant_artifact):
            p.error("--accuracy-probe needs --artifact (the f32 "
                    "reference) and --quant-artifact (the int8 sibling)")
        import numpy as np
        feeds = labels = None
        if args.probe_npz:
            blob = np.load(args.probe_npz)
            arr = blob["data"].astype(np.float32)
            bs = args.probe_batch
            feeds = [{"data": arr[i:i + bs]}
                     for i in range(0, len(arr) - bs + 1, bs)]
            if "labels" in blob.files:
                labels = blob["labels"][:len(feeds) * bs]
        res = measure_accuracy(
            args.artifact, args.quant_artifact, feeds=feeds,
            labels=labels, examples=args.probe_examples,
            batch=args.probe_batch, seed=args.seed)
        line = json.dumps(res)
        print(line)
        if args.out:
            with open(args.out, "w") as f:
                f.write(line)
        return

    if url:
        target = url
        shape = tuple(int(x) for x in args.shape.split(",")) \
            if args.shape else None
    else:
        from mxnet_tpu.serve import Server
        if args.generate or args.recommend:
            target = Server(args.artifact)
        else:
            target = Server(args.artifact, buckets=args.buckets)
        shape = None

    if args.recommend:
        def run_phase(conc, reqs):
            return measure_recommend(
                target, concurrency=conc, requests=reqs,
                mean_ids=args.mean_ids, zipf=args.zipf,
                rows=args.reco_rows, timeout_ms=args.timeout_ms,
                retries=args.retries, seed=args.seed,
                conn_retries=conn_retries)
    elif args.generate:
        def run_phase(conc, reqs):
            return measure_generate(
                target, users=conc, requests=reqs,
                prompt_len=args.prompt_len,
                prompt_dist=args.prompt_dist, max_new=args.max_new,
                output_dist=args.output_dist,
                temperature=args.temperature,
                timeout_ms=args.timeout_ms, retries=args.retries,
                seed=args.seed, vocab=args.vocab,
                max_prompt_len=args.max_prompt_len,
                max_context=args.max_context,
                resume_evicted=resume_evicted,
                conn_retries=conn_retries)
    else:
        def run_phase(conc, reqs):
            return measure(target, concurrency=conc, requests=reqs,
                           qps=args.qps, rows=args.rows,
                           timeout_ms=args.timeout_ms, shape=shape,
                           retries=args.retries,
                           conn_retries=conn_retries)
    if args.profile:
        res = measure_profile(args.profile, run_phase,
                              args.concurrency, args.requests)
    else:
        res = run_phase(args.concurrency, args.requests)
    if not url:
        target.close(drain=True)
    if args.scrape_metrics:
        res["prometheus"] = scrape_prometheus(url)
        assert res["prometheus"]["families"] > 0, \
            "/metrics exposition parsed but held no metric families"
    line = json.dumps(res)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line)


if __name__ == "__main__":
    main()
