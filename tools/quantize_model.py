"""Post-training int8 quantization: checkpoint -> format_version-4
``.mxtpu`` artifact (calibrated activation ranges, per-channel int8
weights baked into the StableHLO, ~4x smaller weight payload).

    python tools/quantize_model.py --prefix model --epoch 10 \
        --data-shape 32,3,224,224 --out model_int8.mxtpu \
        [--calib-npz calib.npz] [--calib-batches 8] [--dynamic-batch]

Calibration data: ``--calib-npz`` (an .npz whose arrays are batches of
the data input, concatenated along axis 0) when you have a labelled
sample of production traffic; otherwise deterministic synthetic batches
from ``--seed`` (fine for pipeline tests, NOT for deployment scales).
The whole calibration pass performs exactly ONE device->host transfer
(see mxnet_tpu/quant/calibrate.py).

Prints one JSON line: artifact path/bytes, f32-vs-int8 weight payload,
quantized and skipped sites (each skip with its reason).
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _calib_batches(args, shape):
    import numpy as np
    n = args.calib_batches
    if args.calib_npz:
        data = np.load(args.calib_npz)
        arr = np.concatenate([data[k] for k in sorted(data.files)], axis=0)
        arr = arr.astype(np.float32)
        bs = shape[0]
        batches = [arr[i:i + bs] for i in range(0, len(arr), bs)]
        return [{args.data_name: b} for b in batches[:n] if len(b) == bs]
    rng = np.random.RandomState(args.seed)
    return [{args.data_name: rng.randn(*shape).astype(np.float32)}
            for _ in range(n)]


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--prefix", required=True)
    p.add_argument("--epoch", type=int, required=True)
    p.add_argument("--data-shape", required=True,
                   help="comma dims incl. calibration batch, e.g. "
                        "32,3,224,224")
    p.add_argument("--data-name", default="data")
    p.add_argument("--out", required=True)
    p.add_argument("--platforms", default=None,
                   help="comma list, e.g. tpu (default: current backend)")
    p.add_argument("--dynamic-batch", action="store_true",
                   help="symbolic batch dim: one int8 artifact serves "
                        "every bucket of the serve engine cache")
    p.add_argument("--calib-npz", default=None,
                   help=".npz of real calibration batches (data input, "
                        "concat on axis 0); default: synthetic from "
                        "--seed")
    p.add_argument("--calib-batches", type=int, default=8)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--excluded", default=None,
                   help="comma list of layer names to keep f32")
    p.add_argument("--num-calib-examples", type=int, default=None)
    p.add_argument("--platform", default=None, choices=[None, "cpu"],
                   help="backend to run calibration + export on")
    args = p.parse_args()
    if args.platform == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")

    import mxnet_tpu as mx
    from mxnet_tpu import quant
    sym, arg_params, aux_params = mx.model.load_checkpoint(args.prefix,
                                                           args.epoch)
    shape = tuple(int(x) for x in args.data_shape.split(","))
    plats = args.platforms.split(",") if args.platforms else None
    excluded = (tuple(s for s in args.excluded.split(",") if s)
                if args.excluded else ())
    meta = quant.export_quantized(
        sym, arg_params, aux_params, _calib_batches(args, shape),
        {args.data_name: shape}, args.out, excluded=excluded,
        num_calib_examples=args.num_calib_examples, platforms=plats,
        dynamic_batch=args.dynamic_batch)
    q = meta["quant"]
    print(json.dumps({
        "artifact": args.out,
        "bytes": os.path.getsize(args.out),
        "format_version": meta["format_version"],
        "weight_bytes": q["weight_bytes"],
        "weight_payload_ratio": round(
            q["weight_bytes"]["int8"] / q["weight_bytes"]["f32"], 4)
            if q["weight_bytes"]["f32"] else None,
        "sites": q["sites"],
        "skipped": q["skipped"],
        "calibration": q["calibration"],
    }))


if __name__ == "__main__":
    main()
