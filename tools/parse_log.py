#!/usr/bin/env python
"""Parse a training log into a markdown (or TSV) table.

Parity: /root/reference/tools/parse_log.py — same log grammar (the
``Epoch[N] Train-metric=V`` / ``Validation-metric=V`` / ``Time cost=V``
lines our fit loops and Speedometer emit match the reference's) and the
same output formats.

Usage: python tools/parse_log.py train.log [--format markdown|none]
       [--metric-names accuracy ce]
"""
import argparse
import re


def parse(lines, metric_names):
    pats = ([re.compile(r".*Epoch\[(\d+)\] Train-%s.*=([.\d]+)" % s)
             for s in metric_names]
            + [re.compile(r".*Epoch\[(\d+)\] Validation-%s.*=([.\d]+)" % s)
               for s in metric_names]
            + [re.compile(r".*Epoch\[(\d+)\] Time.*=([.\d]+)")])
    # data[epoch] = [sum, count] per column (train metrics, val metrics, time)
    data = {}
    for line in lines:
        for i, pat in enumerate(pats):
            m = pat.match(line)
            if m is not None:
                epoch, val = int(m.group(1)), float(m.group(2))
                cols = data.setdefault(epoch, [[0.0, 0] for _ in pats])
                cols[i][0] += val
                cols[i][1] += 1
                break
    return data


def mean(col):
    return col[0] / col[1] if col[1] else float("nan")


def main():
    p = argparse.ArgumentParser(description="Parse training output log")
    p.add_argument("logfile", help="the log file to parse")
    p.add_argument("--format", default="markdown",
                   choices=["markdown", "none"])
    p.add_argument("--metric-names", nargs="+", default=["accuracy"],
                   help="metric names to look for in the log")
    args = p.parse_args()

    with open(args.logfile) as f:
        data = parse(f.readlines(), args.metric_names)

    heads = (["train-" + s for s in args.metric_names]
             + ["val-" + s for s in args.metric_names] + ["time"])
    if args.format == "markdown":
        print("| epoch | " + " | ".join(heads) + " |")
        print("| --- " * (len(heads) + 1) + "|")
        for epoch in sorted(data):
            cols = data[epoch]
            cells = ["%f" % mean(c) for c in cols[:-1]]
            print("| %2d | %s | %.1f |"
                  % (epoch + 1, " | ".join(cells), mean(cols[-1])))
    else:
        print("\t".join(["epoch"] + heads))
        for epoch in sorted(data):
            cols = data[epoch]
            print("\t".join(["%2d" % (epoch + 1)]
                            + ["%f" % mean(c) for c in cols[:-1]]
                            + ["%.1f" % mean(cols[-1])]))


if __name__ == "__main__":
    main()
