"""Serve an ``.mxtpu`` AOT artifact over HTTP with dynamic
micro-batching and admission control.

    python tools/serve.py --artifact model.mxtpu --port 8080 \
        [--buckets 1,8,32] [--batch-timeout-ms 2] [--queue-depth 256] \
        [--timeout-ms 1000] [--no-warmup] [--verbose]

Endpoints (see mxnet_tpu/serve/http.py):
    POST /v1/predict   {"inputs": {"data": [[...]]}}     (predict mode)
    POST /v1/generate  {"prompt": [ids], ...}            (generate mode)
    GET  /metrics      per-bucket p50/p95/p99, occupancy, padding waste
                       (generate mode: tokens/s, TTFT/TPOT, page occ.)
    GET  /healthz

The artifact kind picks the mode: a format_version-3 generate artifact
(serving.export_generate) starts the continuous-batching decode engine;
anything else starts the predict micro-batcher.

SIGINT/SIGTERM triggers a graceful drain: the listener stops accepting,
every admitted request finishes, then the final metrics snapshot is
printed to stdout.
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--artifact", required=True)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8080)
    p.add_argument("--buckets", default=None,
                   help="comma batch buckets, e.g. 1,8,32 (default: "
                        "MXNET_SERVE_BUCKETS for dynamic artifacts, the "
                        "frozen batch for fixed ones)")
    p.add_argument("--batch-timeout-ms", type=float, default=None)
    p.add_argument("--queue-depth", type=int, default=None)
    p.add_argument("--timeout-ms", type=float, default=None)
    p.add_argument("--cache-engines", type=int, default=None)
    p.add_argument("--drain-tokens", type=int, default=None,
                   help="generate mode: per-sequence token budget a "
                        "graceful drain grants before eviction "
                        "(default MXNET_SERVE_DRAIN_TOKENS)")
    p.add_argument("--max-new-tokens", type=int, default=64,
                   help="generate mode: default completion budget when "
                        "the request does not set one")
    p.add_argument("--no-warmup", action="store_true")
    p.add_argument("--platform", default=None, choices=[None, "cpu"],
                   help="pin jax to this backend before loading")
    p.add_argument("--verbose", action="store_true")
    args = p.parse_args()

    if args.platform == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")

    from mxnet_tpu.serve import (GenerateConfig, ServeConfig, Server,
                                 serve_http)
    from mxnet_tpu.serving import GenerateModel, load_artifact

    model = load_artifact(args.artifact)
    if isinstance(model, GenerateModel):
        cfg = GenerateConfig(
            queue_depth=args.queue_depth,
            timeout_ms=args.timeout_ms,
            drain_tokens=args.drain_tokens,
            max_new_tokens=args.max_new_tokens,
            warmup=False if args.no_warmup else None)
    else:
        cfg = ServeConfig(
            buckets=args.buckets,
            batch_timeout_ms=args.batch_timeout_ms,
            queue_depth=args.queue_depth,
            timeout_ms=args.timeout_ms,
            cache_engines=args.cache_engines,
            warmup=False if args.no_warmup else None)
    server = Server(model, config=cfg)
    front = serve_http(server, args.host, args.port, verbose=args.verbose)
    banner = {"serving": args.artifact, "mode": server.mode,
              "url": front.address}
    if server.mode == "generate":
        spec = server.session.spec
        banner["slots"] = spec.max_slots
        banner["kv_pages"] = server.session.cache.total_pages
        banner["page_size"] = spec.page_size
    else:
        banner["buckets"] = list(server.buckets)
    print(json.dumps(banner), flush=True)

    done = threading.Event()

    def _shutdown(signum, frame):
        done.set()

    signal.signal(signal.SIGINT, _shutdown)
    signal.signal(signal.SIGTERM, _shutdown)
    done.wait()
    print("draining...", file=sys.stderr, flush=True)
    front.stop(drain=True)
    print(json.dumps(server.metrics()), flush=True)


if __name__ == "__main__":
    main()
