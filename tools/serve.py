"""Serve an ``.mxtpu`` AOT artifact over HTTP with dynamic
micro-batching and admission control.

    python tools/serve.py --artifact model.mxtpu --port 8080 \
        [--buckets 1,8,32] [--batch-timeout-ms 2] [--queue-depth 256] \
        [--timeout-ms 1000] [--no-warmup] [--verbose]

Fleet mode (a replica behind ``tools/route.py``):

    python tools/serve.py --artifact model.mxtpu --port 0 \
        --register http://router:8090 --model-name resnet --model-version v1

``--register`` makes this process a fleet replica: it announces itself
to the router (id, url, (model, version), artifact identity), heartbeats
readiness + a perfmodel-derived load summary every
``MXNET_FLEET_HEARTBEAT_S``, and deregisters before draining so the
router migrates traffic with zero drops. Registration implies
``--warm-async``: the listener comes up immediately and the replica
reports not-ready ("warming") until engine compiles finish. A replica
also tracks the fleet's fencing epoch (router replies + request
stamps): requests carrying an older epoch are 409'd and the announcer
refuses to re-register with a demoted router — how a revived stale
primary is kept from split-braining an HA fleet (docs/fleet.md).

Endpoints (see mxnet_tpu/serve/http.py):
    POST /v1/predict   {"inputs": {"data": [[...]]}}     (predict mode)
    POST /v1/generate  {"prompt": [ids], ...}            (generate mode)
    GET  /metrics      per-bucket p50/p95/p99, occupancy, padding waste
                       (generate mode: tokens/s, TTFT/TPOT, page occ.)
    GET  /healthz      combined legacy probe
    GET  /livez        liveness    GET /readyz  readiness (+reason)
    GET  /info         artifact identity / wire geometry

The artifact kind picks the mode: a generate artifact
(serving.export_generate, format_version 3 or 5) starts the
continuous-batching decode engine; anything else starts the predict
micro-batcher. A format_version-5 artifact bundles chunked prefill and
(optionally) an int8 draft model — ``--draft auto|on|off`` controls
speculative decoding against the bundled draft.

SIGINT/SIGTERM triggers a graceful drain: deregister from the fleet
(if registered), stop accepting, finish every admitted request, then
print the final metrics snapshot to stdout.
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--artifact", required=True)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8080)
    p.add_argument("--buckets", default=None,
                   help="comma batch buckets, e.g. 1,8,32 (default: "
                        "MXNET_SERVE_BUCKETS for dynamic artifacts, the "
                        "frozen batch for fixed ones)")
    p.add_argument("--batch-timeout-ms", type=float, default=None)
    p.add_argument("--queue-depth", type=int, default=None)
    p.add_argument("--timeout-ms", type=float, default=None)
    p.add_argument("--cache-engines", type=int, default=None)
    p.add_argument("--drain-tokens", type=int, default=None,
                   help="generate mode: per-sequence token budget a "
                        "graceful drain grants before eviction "
                        "(default MXNET_SERVE_DRAIN_TOKENS)")
    p.add_argument("--max-new-tokens", type=int, default=64,
                   help="generate mode: default completion budget when "
                        "the request does not set one")
    p.add_argument("--draft", default="auto", choices=["auto", "on", "off"],
                   help="generate mode: speculative decoding with the "
                        "artifact's bundled int8 draft model. auto "
                        "speculates iff the artifact has one, on "
                        "requires it, off forces plain decode")
    p.add_argument("--no-warmup", action="store_true")
    p.add_argument("--register", default=None, metavar="ROUTER_URL",
                   help="fleet mode: register with this tools/route.py "
                        "router and heartbeat readiness + load")
    p.add_argument("--replica-id", default=None,
                   help="fleet replica id (default host-pid)")
    p.add_argument("--model-name", default="default",
                   help="model this replica serves, for routing and "
                        "traffic splits")
    p.add_argument("--model-version", default="0",
                   help="artifact version, for blue/green + canarying")
    p.add_argument("--warm-async", action="store_true",
                   help="start the HTTP listener before engine warmup; "
                        "/readyz reports 'warming' until compiles "
                        "finish (implied by --register)")
    p.add_argument("--platform", default=None, choices=[None, "cpu"],
                   help="pin jax to this backend before loading")
    p.add_argument("--verbose", action="store_true")
    args = p.parse_args()

    if args.platform == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")

    from mxnet_tpu.serve import (GenerateConfig, ServeConfig, Server,
                                 serve_http)
    from mxnet_tpu.serving import (GenerateModel, artifact_identity,
                                   load_artifact)

    warm_async = args.warm_async or bool(args.register)
    identity = artifact_identity(args.artifact)
    model = load_artifact(args.artifact)
    if isinstance(model, GenerateModel):
        cfg = GenerateConfig(
            queue_depth=args.queue_depth,
            timeout_ms=args.timeout_ms,
            drain_tokens=args.drain_tokens,
            max_new_tokens=args.max_new_tokens,
            speculative={"auto": None, "on": True,
                         "off": False}[args.draft],
            warmup=False if (args.no_warmup or warm_async) else None)
    else:
        cfg = ServeConfig(
            buckets=args.buckets,
            batch_timeout_ms=args.batch_timeout_ms,
            queue_depth=args.queue_depth,
            timeout_ms=args.timeout_ms,
            cache_engines=args.cache_engines,
            warmup=False if (args.no_warmup or warm_async) else None)
    server = Server(model, config=cfg, auto_start=not warm_async)
    server.model_name = args.model_name
    server.model_version = args.model_version
    server.identity = identity
    if warm_async:
        server.warmup_async()
    front = serve_http(server, args.host, args.port, verbose=args.verbose)
    banner = {"serving": args.artifact, "mode": server.mode,
              "url": front.address, "model": args.model_name,
              "version": args.model_version}
    if server.mode == "generate":
        spec = server.session.spec
        banner["slots"] = spec.max_slots
        banner["kv_pages"] = server.session.cache.total_pages
        banner["page_size"] = spec.page_size
        banner["chunked_prefill"] = server.session.chunked
        banner["speculative"] = server.session.speculative
        if server.session.speculative:
            banner["speculate_k"] = server.session.speculate_k
    else:
        banner["buckets"] = list(server.buckets)

    announcer = None
    if args.register:
        import socket
        from mxnet_tpu.fleet import ReplicaAnnouncer
        rid = args.replica_id or ("%s-%d" % (socket.gethostname(),
                                             os.getpid()))
        info = {"id": rid, "url": front.address,
                "model": args.model_name, "version": args.model_version,
                "mode": server.mode, "identity": identity,
                "pid": os.getpid()}
        # layout fingerprint (parallel/layout.py): the router refuses
        # traffic splits that mix disagreeing fingerprints — a hop
        # cursor is only portable between layout-identical replicas.
        # None for artifacts without layout metadata (predict, old
        # exports); the router exempts those.
        from mxnet_tpu.serving import artifact_layout
        info["layout"] = artifact_layout(args.artifact)
        if server.mode == "generate":
            # the router chunks generate hops; it needs the prefill
            # window to know where resume points stop being admissible
            sp = server.session.spec
            info["spec"] = {"vocab": sp.vocab,
                            "max_prompt_len": sp.max_prompt_len,
                            "max_context": sp.max_context,
                            "chunked_prefill": server.session.chunked,
                            "speculative": server.session.speculative}
        announcer = ReplicaAnnouncer(args.register, info,
                                     server.load_status)
        announcer.start()
        banner["replica_id"] = rid
        banner["router"] = args.register
    print(json.dumps(banner), flush=True)

    done = threading.Event()

    def _shutdown(signum, frame):
        done.set()

    signal.signal(signal.SIGINT, _shutdown)
    signal.signal(signal.SIGTERM, _shutdown)
    done.wait()
    print("draining...", file=sys.stderr, flush=True)
    if announcer is not None:
        # leave rotation BEFORE draining: the router re-routes new
        # traffic (and migrates decode sessions via their cursors)
        # while this process finishes what it already admitted
        announcer.stop(deregister=True)
    front.stop(drain=True)
    final = server.metrics()
    if announcer is not None:
        from mxnet_tpu.fleet import fencing
        final["fleet_epoch"] = fencing.current()
        final["stale_router_rejections"] = \
            announcer.stale_router_rejections
    print(json.dumps(final), flush=True)


if __name__ == "__main__":
    main()
