"""KVStore push/pull latency probe — the BASELINE.json "kvstore push/pull
µs" metric (reference analog: tools/bandwidth/measure.py, which times
push/pull of network-sized buffers through the kvstore).

Times the full product path: per-device gradient reduce, optional wire
compression, store update, and pull copy-out, for ResNet-50-ish key sizes.
Runs on CPU or TPU (whatever backend jax resolves; pass --platform cpu to
pin). Under a tools/launch.py group the push crosses processes
(dist_sync allreduce / dist_async server), so the number covers the real
network leg too.

One JSON line:
{"metric": "kvstore_push_pull_us", "value": <us per push+pull>, ...}
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def measure(kv_type="local", size_mb=1.0, reps=20, compression=None,
            ndev=1):
    import numpy as np
    import mxnet_tpu as mx
    import jax

    n = max(1, int(size_mb * (1 << 20) / 4))
    kv = mx.kv.create(kv_type)
    if compression:
        kv.set_gradient_compression({"type": compression, "threshold": 0.5})
    rng = np.random.RandomState(0)
    val = mx.nd.array(rng.randn(n).astype("f4"))
    kv.init("k", val)
    grads = [mx.nd.array(rng.randn(n).astype("f4")) for _ in range(ndev)]
    out = mx.nd.zeros((n,))

    def once():
        kv.push("k", grads if ndev > 1 else grads[0])
        kv.pull("k", out=out, ignore_sparse=False)
        jax.block_until_ready(out._data)

    once()   # warmup/compile
    t0 = time.perf_counter()
    for _ in range(reps):
        once()
    dt = time.perf_counter() - t0
    us = dt / reps * 1e6
    return {
        "metric": "kvstore_push_pull_us",
        "value": round(us, 1),
        "unit": "us",
        "vs_baseline": None,   # reference publishes no single-host number
        "kv_type": kv_type,
        "size_mb": size_mb,
        "ndev": ndev,
        "compression": compression or "none",
        "wire_bytes": kv._last_wire_bytes,
        # actual bytes moved per rep (compressed pushes move the packed
        # codes, not f32) in gigaBITs/s, comparable with link line rates
        "gbit_per_s": round(
            ((kv._last_wire_bytes or size_mb * (1 << 20)) * ndev
             + size_mb * (1 << 20)) * 8 / dt * reps / 1e9, 3),
    }


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--kv-type", default="local")
    p.add_argument("--size-mb", type=float, default=1.0)
    p.add_argument("--reps", type=int, default=20)
    p.add_argument("--ndev", type=int, default=1)
    p.add_argument("--compression", default=None, choices=[None, "2bit"])
    p.add_argument("--platform", default=None, choices=[None, "cpu"])
    args = p.parse_args()
    if args.platform == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")
    print(json.dumps(measure(args.kv_type, args.size_mb, args.reps,
                             args.compression, args.ndev)))


if __name__ == "__main__":
    main()
