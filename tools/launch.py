#!/usr/bin/env python
"""Local N-process launcher for dist_sync / dist_async training.

Reference analog: ``tools/launch.py`` (which spawns ps-lite schedulers/
servers/workers over ssh/mpirun/yarn). The TPU-native runtime needs no
scheduler or server processes — only N workers pointed at a PJRT
coordination service — so this launcher:

* picks a free coordinator port on localhost,
* spawns N copies of the command with MXNET_COORDINATOR_ADDRESS /
  MXNET_NUM_WORKERS / MXNET_WORKER_RANK set (DMLC_* aliases too, so
  reference-era scripts reading DMLC_NUM_WORKER keep working),
* streams each worker's output with a ``[worker N]`` prefix,
* on any worker failing, kills the rest — then SUPERVISES: up to
  ``--max-restarts`` times (default 3) the whole group is relaunched
  with capped jittered exponential backoff, a fresh coordinator port,
  and ``MXNET_RESUME_DIR`` pointed at the job checkpoint directory so
  workers resume from the last committed snapshot
  (docs/fault_tolerance.md). The group restarts as a unit because rank
  0 hosts the PJRT coordination service — a single rank cannot rejoin a
  running group. A structured JSON failure summary is emitted on stderr
  whenever any attempt failed.

Multi-host launches (one process per host over DCN) use the same
environment contract: ``-H host0,host1,...`` starts one worker per host
over ssh (the reference launcher's ssh mode, tools/launch.py -H), with
MXNET_COORDINATOR_ADDRESS pointed at host 0, a shared per-job
MXNET_KVSTORE_SECRET, and reference-era DMLC_* aliases. ``--dry-run``
prints the exact per-host command instead of executing — the documented
recipe for schedulers that own placement (k8s/slurm: run those commands
yourself, one per host).

Usage::

    # single host, N processes
    python tools/launch.py -n 4 [--env K=V ...] python train.py \
        --kv-store dist_sync

    # two hosts over DCN (one process per host, ssh)
    python tools/launch.py -H host0,host1 \
        --heartbeat-dir /shared/hb python train.py --kv-store dist_sync
"""
import argparse
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import threading


def _load_backoff():
    """The one restart schedule, shared with the serving fleet
    supervisor. Loaded from mxnet_tpu/fleet/supervisor.py by file path
    — that module is stdlib-only, while importing the mxnet_tpu
    *package* would pull jax into the launcher process."""
    import importlib.util
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "mxnet_tpu", "fleet", "supervisor.py")
    spec = importlib.util.spec_from_file_location(
        "_mxtpu_fleet_supervisor", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.backoff_delay


_backoff_delay = _load_backoff()


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _stream(proc, rank_, out):
    for line in proc.stdout:
        out.write("[worker %d] %s" % (rank_, line))
        out.flush()


def _worker_env(addr, num_workers, rank_, hb_dir, extra):
    """The environment contract every worker sees (single- and
    multi-host modes share it)."""
    host0, _, port = addr.rpartition(":")
    env = {
        "MXNET_COORDINATOR_ADDRESS": addr,
        "MXNET_NUM_WORKERS": str(num_workers),
        "MXNET_WORKER_RANK": str(rank_),
        "MXNET_HEARTBEAT_DIR": hb_dir,
        "MXNET_KVSTORE_SECRET": os.environ["MXNET_KVSTORE_SECRET"],
        # reference-era names
        "DMLC_PS_ROOT_URI": host0,
        "DMLC_PS_ROOT_PORT": port,
        "DMLC_NUM_WORKER": str(num_workers),
        "DMLC_WORKER_ID": str(rank_),
        "DMLC_ROLE": "worker",
    }
    for kv in extra:
        k, _, v = kv.partition("=")
        env[k] = v
    return env


def _ssh_command(host, env, command, cwd):
    """One remote worker: ssh <host> '<read secret from stdin> && cd
    <cwd> && env K=V... cmd'. The job secret travels on stdin, NOT in
    argv — /proc/<pid>/cmdline is world-readable on shared hosts."""
    import shlex
    exports = " ".join("%s=%s" % (k, shlex.quote(v))
                       for k, v in sorted(env.items()))
    remote = ("IFS= read -r MXNET_KVSTORE_SECRET && "
              "export MXNET_KVSTORE_SECRET && cd %s && env %s %s"
              % (shlex.quote(cwd), exports,
                 " ".join(shlex.quote(c) for c in command)))
    return ["ssh", "-o", "BatchMode=yes", "-o",
            "StrictHostKeyChecking=accept-new", host, remote]


def _multihost(args):
    """One worker per host entry over ssh (reference launch.py ssh
    launcher). --dry-run prints the per-host commands for scheduler-
    owned placement instead of executing."""
    hosts = [h.strip() for h in args.hosts.split(",") if h.strip()]
    n = args.num_workers or len(hosts)
    port = args.coordinator_port or 9091   # must be pre-agreed: remote
    # ssh accepts user@host; the coordinator address must not carry the
    # user part (workers dial it as a plain network address)
    host0 = hosts[0].rpartition("@")[2]
    addr = "%s:%d" % (host0, port)         # hosts can't ask us for a port
    if "MXNET_KVSTORE_SECRET" not in os.environ:
        import secrets as _secrets
        os.environ["MXNET_KVSTORE_SECRET"] = _secrets.token_hex(16)
    hb_dir = args.heartbeat_dir
    if hb_dir is None:
        hb_dir = tempfile.gettempdir() + "/mxtpu_hb"
        sys.stderr.write(
            "launch.py: no --heartbeat-dir given; per-host %s is NOT "
            "shared, so cross-host failure detection via "
            "get_num_dead_node is off\n" % hb_dir)
    secret = os.environ["MXNET_KVSTORE_SECRET"]
    cmds = []
    for r in range(n):
        host = hosts[r % len(hosts)]
        env = _worker_env(addr, n, r, hb_dir, args.env)
        env.pop("MXNET_KVSTORE_SECRET")  # shipped on stdin, not argv
        cmds.append((r, host, _ssh_command(host, env, args.command,
                                           os.getcwd())))
    if args.dry_run:
        sys.stderr.write(
            "launch.py: export MXNET_KVSTORE_SECRET (same value "
            "everywhere) before running these; each command reads it "
            "from stdin\n")
        for r, host, cmd in cmds:
            # runnable as printed: the operator's env supplies the secret
            print("[rank %d @ %s] printf '%%s\\n' "
                  "\"$MXNET_KVSTORE_SECRET\" | %s" % (r, host,
                                                      " ".join(cmd)))
        return 0
    procs = []
    threads = []
    for r, host, cmd in cmds:
        p = subprocess.Popen(cmd, stdin=subprocess.PIPE,
                             stdout=subprocess.PIPE,
                             stderr=subprocess.STDOUT, text=True)
        try:
            p.stdin.write(secret + "\n")
            p.stdin.close()
        except (BrokenPipeError, OSError):
            pass  # ssh died instantly; _wait_group reaps it and
            # terminates the rest of the group
        procs.append(p)
        t = threading.Thread(target=_stream, args=(p, r, sys.stdout),
                             daemon=True)
        t.start()
        threads.append(t)
    rc, _ = _wait_group(procs, threads)
    return rc


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("-n", "--num-workers", type=int, default=None)
    ap.add_argument("-H", "--hosts", default=None,
                    help="comma-separated host list: one worker per "
                         "entry over ssh (multi-host DCN mode)")
    ap.add_argument("--coordinator-port", type=int, default=None)
    ap.add_argument("--heartbeat-dir", default=None,
                    help="shared-filesystem dir for cross-host failure "
                         "detection (multi-host mode)")
    ap.add_argument("--dry-run", action="store_true",
                    help="print per-host commands instead of executing")
    ap.add_argument("--env", action="append", default=[],
                    help="extra K=V for the workers")
    ap.add_argument("--ddp", action="store_true",
                    help="bucketed data-parallel gradient all-reduce: "
                         "export MXNET_DDP=1 to every worker so dist_sync "
                         "training reduces gradients inside the jitted "
                         "step (parallel/ddp.py) instead of through the "
                         "kvstore (docs/distributed.md)")
    ap.add_argument("--ddp-bucket-mb", type=float, default=None,
                    help="override the gradient bucket size in MiB "
                         "(MXNET_DDP_BUCKET_MB; default: auto from the "
                         "interconnect cost model)")
    ap.add_argument("--max-restarts", type=int, default=3,
                    help="supervised restarts after a worker death "
                         "(single-host mode; 0 disables)")
    ap.add_argument("--restart-backoff", type=float, default=1.0,
                    help="base seconds for the capped jittered "
                         "exponential restart backoff")
    ap.add_argument("--checkpoint-dir", default=None,
                    help="job checkpoint directory (exported as "
                         "MXNET_CHECKPOINT_DIR; restarted workers get it "
                         "as MXNET_RESUME_DIR). Default: a fresh temp dir "
                         "when --max-restarts > 0, else none")
    ap.add_argument("command", nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)
    if not args.command:
        ap.error("no command given")
    # --ddp rides the existing --env plumbing so both the single-host and
    # the ssh multi-host path export the same contract
    if args.ddp:
        args.env = list(args.env) + ["MXNET_DDP=1"]
        if args.ddp_bucket_mb is not None:
            args.env.append("MXNET_DDP_BUCKET_MB=%g" % args.ddp_bucket_mb)
    elif args.ddp_bucket_mb is not None:
        ap.error("--ddp-bucket-mb requires --ddp")
    if args.hosts:
        return _multihost(args)
    if not args.num_workers:
        ap.error("-n is required in single-host mode")

    import json
    import random
    import shlex
    import time
    # per-job kvstore auth secret: separate worker processes must share it
    # to talk to the rank-0 async server (async_server.py trust model)
    if "MXNET_KVSTORE_SECRET" not in os.environ:
        import secrets as _secrets
        os.environ["MXNET_KVSTORE_SECRET"] = _secrets.token_hex(16)
    if args.dry_run:
        addr = "127.0.0.1:%d" % (args.coordinator_port or _free_port())
        sys.stderr.write(
            "launch.py: export MXNET_KVSTORE_SECRET (same value for "
            "every worker) before running these\n")
        for r in range(args.num_workers):
            env = _worker_env(addr, args.num_workers, r, "<heartbeat-dir>",
                              args.env)
            env.pop("MXNET_KVSTORE_SECRET")  # never print secrets in argv
            print("[rank %d @ localhost] env %s %s"
                  % (r, " ".join("%s=%s" % (k, shlex.quote(v))
                                 for k, v in sorted(env.items())),
                     " ".join(args.command)))
        return 0

    # a checkpoint dir the launcher knows about is what makes restarts
    # useful: restarted workers get it as MXNET_RESUME_DIR and continue
    # instead of recomputing from scratch
    ckpt_dir = args.checkpoint_dir
    if ckpt_dir is None:
        for kv in args.env:
            if kv.startswith(("MXNET_CHECKPOINT_DIR=", "MXNET_RESUME_DIR=")):
                ckpt_dir = kv.partition("=")[2]
    owns_ckpt = False
    if ckpt_dir is None and args.max_restarts > 0:
        ckpt_dir = tempfile.mkdtemp(prefix="mxtpu_ckpt_")
        owns_ckpt = True

    attempts = []
    attempt = 0
    rc = 0
    while True:
        # fresh coordinator port + heartbeat dir per attempt: the old
        # port may sit in TIME_WAIT and stale heartbeat files would make
        # the new incarnation see phantom dead nodes
        port = args.coordinator_port or _free_port()
        addr = "127.0.0.1:%d" % port
        hb_dir = tempfile.mkdtemp(prefix="mxtpu_hb_")
        extra_env = {}
        if ckpt_dir:
            extra_env["MXNET_CHECKPOINT_DIR"] = ckpt_dir
        if attempt > 0:
            extra_env["MXNET_RESUME_DIR"] = ckpt_dir or ""
            # injected faults are first-incarnation-only: the restarted
            # run resumes at the very step the fault fired at, and would
            # otherwise just die there again
            extra_env["MXNET_FAULT_INJECT"] = ""
        tic = time.time()
        procs = []
        threads = []
        for r in range(args.num_workers):
            env = dict(os.environ)
            env.update(_worker_env(addr, args.num_workers, r, hb_dir,
                                   args.env))
            env.update(extra_env)
            p = subprocess.Popen(args.command, env=env,
                                 stdout=subprocess.PIPE,
                                 stderr=subprocess.STDOUT, text=True)
            procs.append(p)
            t = threading.Thread(target=_stream, args=(p, r, sys.stdout),
                                 daemon=True)
            t.start()
            threads.append(t)
        rc, dead = _wait_group(procs, threads)
        shutil.rmtree(hb_dir, ignore_errors=True)
        attempts.append({"attempt": attempt, "rc": rc, "dead_ranks": dead,
                         "duration_s": round(time.time() - tic, 3),
                         "resumed": attempt > 0})
        if rc == 0 or rc == 130 or attempt >= args.max_restarts:
            break
        delay = _backoff_delay(attempt, base=args.restart_backoff,
                               cap=30.0, jitter=0.5, rng=random)
        sys.stderr.write(
            "launch.py: restarting the group (attempt %d/%d) in %.1fs; "
            "workers will resume from %s\n"
            % (attempt + 1, args.max_restarts, delay,
               ckpt_dir or "<no checkpoint dir>"))
        time.sleep(delay)
        attempt += 1
    if rc != 0 or attempt > 0:
        # structured failure summary: one parseable line for fleet tooling
        sys.stderr.write("launch.py: summary %s\n" % json.dumps(
            {"rc": rc, "restarts": attempt,
             "max_restarts": args.max_restarts,
             "checkpoint_dir": ckpt_dir, "attempts": attempts},
            sort_keys=True))
    if owns_ckpt and rc == 0:
        shutil.rmtree(ckpt_dir, ignore_errors=True)
    return rc


def _wait_group(procs, threads):
    """Wait for the group; on the first nonzero exit, terminate the
    stragglers. Returns ``(rc, dead_ranks)``."""
    rc = 0
    dead = []
    try:
        # poll ALL workers: a failed one wedges the rest at their next
        # collective, so on first failure terminate the stragglers
        import time
        pending = set(procs)
        while pending:
            # rank order, not set order: when a death cascades (rank 0
            # dies -> peers abort on the lost coordinator), the lowest
            # dead rank is the root cause and its rc is the one reported
            for p in procs:
                if p not in pending:
                    continue
                r = p.poll()
                if r is None:
                    continue
                pending.discard(p)
                if r != 0 and rc == 0:
                    rc = r
                    dead = [i for i, q in enumerate(procs)
                            if q.poll() not in (None, 0)]
                    sys.stderr.write(
                        "launch.py: worker(s) %s died (rc=%d); "
                        "terminating the group\n" % (dead, r))
                    for q in procs:
                        if q.poll() is None:
                            q.terminate()
            if pending:
                time.sleep(0.2)
    except KeyboardInterrupt:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGINT)
        rc = 130
    for t in threads:
        t.join(timeout=5)
    return rc, dead


if __name__ == "__main__":
    sys.exit(main())
