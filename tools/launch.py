#!/usr/bin/env python
"""Local N-process launcher for dist_sync / dist_async training.

Reference analog: ``tools/launch.py`` (which spawns ps-lite schedulers/
servers/workers over ssh/mpirun/yarn). The TPU-native runtime needs no
scheduler or server processes — only N workers pointed at a PJRT
coordination service — so this launcher:

* picks a free coordinator port on localhost,
* spawns N copies of the command with MXNET_COORDINATOR_ADDRESS /
  MXNET_NUM_WORKERS / MXNET_WORKER_RANK set (DMLC_* aliases too, so
  reference-era scripts reading DMLC_NUM_WORKER keep working),
* streams each worker's output with a ``[worker N]`` prefix,
* on any worker failing, kills the rest and exits non-zero.

Multi-host launches (one process per host over DCN) use the same
environment contract — point MXNET_COORDINATOR_ADDRESS at host 0 and run
one process per host with distinct ranks; this script is the single-host
convenience wrapper the reference's ``-n N`` local mode provided.

Usage::

    python tools/launch.py -n 4 [--env K=V ...] python train.py \
        --kv-store dist_sync
"""
import argparse
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import threading


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _stream(proc, rank_, out):
    for line in proc.stdout:
        out.write("[worker %d] %s" % (rank_, line))
        out.flush()


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("-n", "--num-workers", type=int, required=True)
    ap.add_argument("--coordinator-port", type=int, default=None)
    ap.add_argument("--env", action="append", default=[],
                    help="extra K=V for the workers")
    ap.add_argument("command", nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)
    if not args.command:
        ap.error("no command given")

    port = args.coordinator_port or _free_port()
    addr = "127.0.0.1:%d" % port
    hb_dir = tempfile.mkdtemp(prefix="mxtpu_hb_")
    # per-job kvstore auth secret: separate worker processes must share it
    # to talk to the rank-0 async server (async_server.py trust model)
    if "MXNET_KVSTORE_SECRET" not in os.environ:
        import secrets as _secrets
        os.environ["MXNET_KVSTORE_SECRET"] = _secrets.token_hex(16)
    procs = []
    threads = []
    for r in range(args.num_workers):
        env = dict(os.environ)
        env.update({
            "MXNET_COORDINATOR_ADDRESS": addr,
            "MXNET_NUM_WORKERS": str(args.num_workers),
            "MXNET_WORKER_RANK": str(r),
            "MXNET_HEARTBEAT_DIR": hb_dir,
            # reference-era names
            "DMLC_PS_ROOT_URI": "127.0.0.1",
            "DMLC_PS_ROOT_PORT": str(port),
            "DMLC_NUM_WORKER": str(args.num_workers),
            "DMLC_WORKER_ID": str(r),
            "DMLC_ROLE": "worker",
        })
        for kv in args.env:
            k, _, v = kv.partition("=")
            env[k] = v
        p = subprocess.Popen(args.command, env=env,
                             stdout=subprocess.PIPE,
                             stderr=subprocess.STDOUT, text=True)
        procs.append(p)
        t = threading.Thread(target=_stream, args=(p, r, sys.stdout),
                             daemon=True)
        t.start()
        threads.append(t)

    rc = 0
    try:
        # poll ALL workers: a failed one wedges the rest at their next
        # collective, so on first failure terminate the stragglers
        import time
        pending = set(procs)
        while pending:
            for p in list(pending):
                r = p.poll()
                if r is None:
                    continue
                pending.discard(p)
                if r != 0 and rc == 0:
                    rc = r
                    dead = [i for i, q in enumerate(procs)
                            if q.poll() not in (None, 0)]
                    sys.stderr.write(
                        "launch.py: worker(s) %s died (rc=%d); "
                        "terminating the group\n" % (dead, r))
                    for q in procs:
                        if q.poll() is None:
                            q.terminate()
            if pending:
                time.sleep(0.2)
    except KeyboardInterrupt:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGINT)
        rc = 130
    for t in threads:
        t.join(timeout=5)
    shutil.rmtree(hb_dir, ignore_errors=True)
    return rc


if __name__ == "__main__":
    sys.exit(main())
