"""Gluon imperative-vs-hybridized throughput across the model zoo.

Parity: /root/reference/benchmark/python/gluon/benchmark_gluon.py (the
BASELINE.md measurement-tools row "gluon imperative vs hybrid
throughput"). Same sweep axes — model, batch size, inference/training —
plus the comparison that tool exists for: eager dispatch vs the compiled
CachedOp. On TPU the gap is the whole story (eager pays a PJRT dispatch
per op; hybridized runs ONE XLA program), so the ratio is printed too.

One JSON line per (model, mode, batch, variant):

    {"metric": "gluon_img_per_sec", "model": "resnet18_v1",
     "mode": "inference", "hybrid": true, ...}

Usage: python tools/benchmark_gluon.py [--model resnet18_v1]
       [--batch-size 32] [--num-batches 10] [--type inference]
       [--no-imperative] [--platform cpu]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _input_shape(model):
    return (3, 299, 299) if model.startswith("inception") else (3, 224, 224)


def run_inference(model, batch, steps, hybrid, ctx):
    import numpy as np
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import vision

    net = vision.get_model(model, pretrained=False)
    net.initialize(mx.initializer.Xavier(), ctx=ctx)
    if hybrid:
        net.hybridize(static_alloc=True)
    x = mx.nd.random.uniform(shape=(batch,) + _input_shape(model), ctx=ctx)
    net(x).wait_to_read()                        # warmup/compile
    t0 = time.perf_counter()
    for _ in range(steps):
        out = net(x)
    float(np.asarray(jax.device_get(out._data)).ravel()[0])
    return time.perf_counter() - t0


def run_training(model, batch, steps, hybrid, ctx):
    import numpy as np
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon.model_zoo import vision

    net = vision.get_model(model, pretrained=False)
    net.initialize(mx.initializer.Xavier(), ctx=ctx)
    if hybrid:
        net.hybridize(static_alloc=True)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.01, "momentum": 0.9})
    x = mx.nd.random.uniform(shape=(batch,) + _input_shape(model), ctx=ctx)
    y = mx.nd.array(np.random.randint(0, 1000, (batch,)), ctx=ctx)

    def step():
        with autograd.record():
            out = net(x)
            loss = loss_fn(out, y)
        loss.backward()
        trainer.step(batch)
        return loss

    step().wait_to_read()                        # warmup/compile
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step()
    float(np.asarray(jax.device_get(loss._data)).ravel()[0])
    return time.perf_counter() - t0


def bench(model, batch, steps, mode, variants):
    import jax
    import mxnet_tpu as mx

    on_tpu = jax.devices()[0].platform != "cpu"
    ctx = mx.tpu() if on_tpu else mx.cpu()
    fn = run_inference if mode == "inference" else run_training
    results = {}
    for hybrid in variants:
        dt = fn(model, batch, steps, hybrid, ctx)
        results[hybrid] = batch * steps / dt
        print(json.dumps({
            "metric": "gluon_img_per_sec",
            "model": model, "mode": mode, "hybrid": hybrid,
            "value": round(results[hybrid], 2), "unit": "img/s",
            "batch": batch, "step_ms": round(dt / steps * 1e3, 3),
            "device": jax.devices()[0].device_kind,
        }), flush=True)
    if True in results and False in results:
        print(json.dumps({
            "metric": "gluon_hybridize_speedup", "model": model,
            "mode": mode,
            "value": round(results[True] / results[False], 2), "unit": "x",
        }), flush=True)


def main():
    p = argparse.ArgumentParser(
        description="Gluon model-zoo CNN benchmark (imperative vs hybrid)")
    p.add_argument("--model", default="resnet18_v1",
                   help="any gluon model-zoo name, comma list accepted")
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--num-batches", type=int, default=10)
    p.add_argument("--type", default="inference", dest="mode",
                   choices=["all", "training", "inference"])
    p.add_argument("--no-imperative", action="store_true",
                   help="hybridized only (eager sweeps are slow on big "
                        "zoo models)")
    p.add_argument("--platform", default=None, choices=[None, "cpu"])
    args = p.parse_args()
    if args.platform == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")

    variants = [True] if args.no_imperative else [True, False]
    modes = ["inference", "training"] if args.mode == "all" else [args.mode]
    for model in args.model.split(","):
        for mode in modes:
            bench(model.strip(), args.batch_size, args.num_batches, mode,
                  variants)


if __name__ == "__main__":
    main()
