#!/usr/bin/env python
"""Build SHARDED RecordIO sets for the streaming ingestion tier
(mxnet_tpu/data/ — docs/data.md).

Where tools/im2rec.py packs ONE prefix.rec for the classic single-file
readers, this packer writes ``prefix-00000.rec/.idx .. prefix-0000N``
shard files sized for :class:`mxnet_tpu.data.ShardedRecordStream`'s
file-level + within-file strided partitioning across dp ranks. Three
subcommands:

  # 1) pack an image folder (one label per leaf directory)
  python tools/make_recordio.py images out/train path/to/images \
      --num-shards 8 --resize 256 --quality 95

  # 2) synthetic JPEG images (bench/tests: no dataset download)
  python tools/make_recordio.py synth-images out/synth \
      --num-samples 512 --side 64 --num-shards 4 --seed 0

  # 3) synthetic two-tower interaction records (user, item, rating)
  #    — the streaming feed for examples/train_twotower.py --recordio
  python tools/make_recordio.py twotower out/inter \
      --num-samples 4096 --users 1000 --items 2000 --zipf 1.1

Records are fixed-layout: images carry JPEG payloads under an IRHeader
whose label is the class id; twotower records carry a little-endian
``float32[3] = (user_id, item_id, rating)`` payload decodable with
``RawTensorDecoder((3,))``. Sample ``i`` lands in shard ``i % S`` so
every shard sees an unbiased slice of the sample stream.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def shard_paths(out_prefix, num_shards):
    """The ``prefix-%05d.rec`` path list a packer run produces (and a
    ShardedRecordStream consumes)."""
    return ["%s-%05d.rec" % (out_prefix, s) for s in range(num_shards)]


def write_shards(samples, out_prefix, num_shards):
    """Round-robin ``(label, payload_bytes)`` samples into ``num_shards``
    indexed RecordIO files. Returns the .rec path list.

    ``label`` may be a float or a 1-D float array (multi-label header).
    """
    from mxnet_tpu import recordio as rio
    num_shards = max(1, int(num_shards))
    d = os.path.dirname(out_prefix)
    if d:
        os.makedirs(d, exist_ok=True)
    recs = shard_paths(out_prefix, num_shards)
    writers = [rio.MXIndexedRecordIO(p[:-4] + ".idx", p, "w") for p in recs]
    counts = [0] * num_shards
    try:
        for i, (label, payload) in enumerate(samples):
            s = i % num_shards
            lab = np.asarray(label, dtype=np.float32).reshape(-1)
            if lab.size == 1:
                header = rio.IRHeader(0, float(lab[0]), i, 0)
            else:
                header = rio.IRHeader(lab.size, lab, i, 0)
            writers[s].write_idx(counts[s], rio.pack(header, payload))
            counts[s] += 1
    finally:
        for w in writers:
            w.close()
    return recs


# --------------------------------------------------------------- generators

def iter_image_folder(root, resize=0, quality=95, exts=(".jpg", ".jpeg",
                                                        ".png")):
    """Yield (label, jpeg_bytes) from an image folder — one label per
    leaf directory, tools/im2rec.py's --recursive labeling."""
    import cv2
    cat = {}
    for path, dirs, files in os.walk(root, followlinks=True):
        dirs.sort()
        files.sort()
        for fname in files:
            fpath = os.path.join(path, fname)
            if os.path.splitext(fname)[1].lower() not in exts:
                continue
            img = cv2.imread(fpath, cv2.IMREAD_COLOR)
            if img is None:
                print("skipping unreadable image: %s" % fpath,
                      file=sys.stderr)
                continue
            if resize:
                h, w = img.shape[:2]
                scale = float(resize) / min(h, w)
                img = cv2.resize(img, (int(w * scale + 0.5),
                                       int(h * scale + 0.5)))
            ok, buf = cv2.imencode(
                ".jpg", img, [cv2.IMWRITE_JPEG_QUALITY, int(quality)])
            if not ok:
                continue
            if path not in cat:
                cat[path] = len(cat)
            yield cat[path], buf.tobytes()


def iter_synth_images(num_samples, side=64, num_classes=10, quality=80,
                      seed=0):
    """Yield (label, jpeg_bytes) synthetic images — bench/tests feedstock
    with no dataset download."""
    import cv2
    rng = np.random.RandomState(seed)
    for i in range(num_samples):
        img = rng.randint(0, 255, size=(side, side, 3), dtype=np.uint8)
        ok, buf = cv2.imencode(
            ".jpg", img, [cv2.IMWRITE_JPEG_QUALITY, int(quality)])
        assert ok
        yield i % num_classes, buf.tobytes()


def iter_twotower(num_samples, users, items, dim=16, zipf=1.1, noise=0.01,
                  seed=0):
    """Yield (rating, float32[3] payload) synthetic two-tower interaction
    records: Zipf-skewed (user, item) pairs rated by a hidden
    factorization — the same generator shape as
    examples/train_twotower.py, but streamed to disk."""
    rng = np.random.RandomState(seed)
    gt_u = (rng.randn(users, dim) / np.sqrt(dim)).astype(np.float32)
    gt_i = (rng.randn(items, dim) / np.sqrt(dim)).astype(np.float32)

    def zipf_ids(n, vocab):
        if zipf <= 0:
            return rng.randint(0, vocab, size=n)
        ranks = np.arange(1, vocab + 1, dtype=np.float64)
        p = ranks ** (-zipf)
        p /= p.sum()
        return rng.choice(vocab, size=n, p=p)

    u_ids = zipf_ids(num_samples, users)
    i_ids = zipf_ids(num_samples, items)
    ratings = ((gt_u[u_ids] * gt_i[i_ids]).sum(-1)
               + noise * rng.randn(num_samples)).astype(np.float32)
    for u, it, r in zip(u_ids, i_ids, ratings):
        rec = np.array([u, it, r], dtype=np.float32)
        yield float(r), rec.tobytes()


# ---------------------------------------------------------------------- CLI

def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Pack sharded RecordIO sets for the streaming tier")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("images", help="pack an image folder")
    p.add_argument("out_prefix")
    p.add_argument("root")
    p.add_argument("--num-shards", type=int, default=4)
    p.add_argument("--resize", type=int, default=0)
    p.add_argument("--quality", type=int, default=95)

    p = sub.add_parser("synth-images", help="pack synthetic JPEG images")
    p.add_argument("out_prefix")
    p.add_argument("--num-samples", type=int, default=256)
    p.add_argument("--side", type=int, default=64)
    p.add_argument("--num-classes", type=int, default=10)
    p.add_argument("--num-shards", type=int, default=4)
    p.add_argument("--quality", type=int, default=80)
    p.add_argument("--seed", type=int, default=0)

    p = sub.add_parser("twotower",
                       help="pack synthetic two-tower interactions")
    p.add_argument("out_prefix")
    p.add_argument("--num-samples", type=int, default=4096)
    p.add_argument("--users", type=int, default=1000)
    p.add_argument("--items", type=int, default=2000)
    p.add_argument("--dim", type=int, default=16)
    p.add_argument("--zipf", type=float, default=1.1)
    p.add_argument("--noise", type=float, default=0.01)
    p.add_argument("--num-shards", type=int, default=4)
    p.add_argument("--seed", type=int, default=0)

    args = ap.parse_args(argv)
    if args.cmd == "images":
        samples = iter_image_folder(args.root, resize=args.resize,
                                    quality=args.quality)
    elif args.cmd == "synth-images":
        samples = iter_synth_images(args.num_samples, side=args.side,
                                    num_classes=args.num_classes,
                                    quality=args.quality, seed=args.seed)
    else:
        samples = iter_twotower(args.num_samples, users=args.users,
                                items=args.items, dim=args.dim,
                                zipf=args.zipf, noise=args.noise,
                                seed=args.seed)
    recs = write_shards(samples, args.out_prefix, args.num_shards)
    from mxnet_tpu.data import ShardedRecordStream
    total = ShardedRecordStream(recs, shuffle=False).records_per_epoch()
    print("wrote %d records across %d shards: %s"
          % (total, len(recs), ", ".join(os.path.basename(r) for r in recs)))
    return recs


if __name__ == "__main__":
    main()
