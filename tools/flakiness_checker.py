#!/usr/bin/env python
"""Check a given test for flakiness by running it many times.

Parity: /root/reference/tools/flakiness_checker.py (same job: take a test
spec + trial count, re-run with varying seeds, report). Differences: our
suite is pytest (the reference was nosetests), so the spec is any pytest
node id (``tests/test_ops.py::test_conv``) or the reference-style
``test_module.test_name`` form, and the seed rides MXNET_TEST_SEED, which
``mxnet_tpu.test_utils.with_seed`` honors.

Usage: python tools/flakiness_checker.py tests/test_metric_io.py::test_acc
       [-n 100] [-s SEED] [-v]
"""
import argparse
import os
import re
import subprocess
import sys
import random

DEFAULT_NUM_TRIALS = 100


def find_test_path(test_file):
    """Map a bare module name (reference style: ``test_operator``) to a
    path under tests/."""
    if os.path.exists(test_file):
        return test_file
    base = os.path.basename(test_file)
    if not base.endswith(".py"):
        base += ".py"
    top = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for root, _, files in os.walk(os.path.join(top, "tests")):
        if base in files:
            return os.path.join(root, base)
    raise FileNotFoundError("could not find %s under tests/" % test_file)


def parse_spec(spec):
    if "::" in spec:                       # pytest node id
        path, name = spec.split("::", 1)
        return find_test_path(path), name
    m = re.match(r"(.+)\.(test_\w+)$", spec)  # reference dotted form
    if m:
        return find_test_path(m.group(1)), m.group(2)
    return find_test_path(spec), None


def run_test_trials(args):
    path, name = parse_spec(args.test)
    node = path if name is None else "%s::%s" % (path, name)
    verbosity = [] if args.verbose else ["-q", "--no-header"]
    failures = 0
    for i in range(args.trials):
        seed = args.seed if args.seed is not None \
            else random.randint(0, 2**31 - 1)
        env = dict(os.environ, MXNET_TEST_SEED=str(seed))
        res = subprocess.run(
            [sys.executable, "-m", "pytest", node, "-x"] + verbosity,
            env=env, capture_output=not args.verbose, text=True)
        if res.returncode != 0:
            failures += 1
            print("FAILED trial %d/%d (seed %d)" % (i + 1, args.trials, seed))
            if not args.verbose and res.stdout:
                print(res.stdout.strip().splitlines()[-1])
        elif args.verbose:
            print("passed trial %d/%d (seed %d)" % (i + 1, args.trials, seed))
    return failures


def main():
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("test", help="pytest node id (tests/test_x.py::test_y), "
                                "file path, or reference-style module.name")
    p.add_argument("-n", "--trials", type=int, default=DEFAULT_NUM_TRIALS)
    p.add_argument("-s", "--seed", type=int, default=None,
                   help="fixed seed for every trial (default: random per "
                        "trial)")
    p.add_argument("-v", "--verbose", action="store_true")
    args = p.parse_args()

    failures = run_test_trials(args)
    print("%d/%d trials failed" % (failures, args.trials))
    sys.exit(1 if failures else 0)


if __name__ == "__main__":
    main()
