"""Run a fleet router in front of ``tools/serve.py`` replicas.

    python tools/route.py --port 8090 [--verbose]
    python tools/route.py --port 8090 --journal /var/lib/mxtpu/fleet
    python tools/route.py --standby --journal /var/lib/mxtpu/fleet
    python tools/route.py --standby --journal /var/lib/mxtpu/replica \
        --replicate-from http://primary:8090

Replicas self-register: start each ``tools/serve.py`` with
``--register http://127.0.0.1:8090`` and it appears in the rotation as
soon as its warmup finishes (push registration + heartbeats; nothing to
configure here). ``--replicas url1,url2`` additionally seeds the
registry from running non-fleet servers by scraping their ``/info``;
static seeds send no heartbeats, so they are exempt from the staleness
sweep and trusted until a proxied request to them fails.

High availability (``--journal DIR``): the router write-ahead logs
every registry mutation and generate hop cursor into DIR
(mxnet_tpu/fleet/journal.py) and refreshes a lease file there. A
second ``route.py --standby --journal DIR`` process tails the journal;
when the lease content stops changing for ``--lease-timeout-s``
monotonic seconds it replays the journal, claims the next fencing
epoch, rebinds the primary's address, and resumes every in-flight
generate session from its last durable hop cursor. A revived stale
primary is fenced out twice over: its startup lease guard refuses to
run while a live holder exists (exit 2 unless ``--force-primary``),
and replicas 409 any request it stamps with its old epoch.

Shared storage is optional: ``--standby --replicate-from URL`` streams
the primary's journal over its HTTP front end into the standby's own
``--journal`` directory (snapshot bootstrap + offset-resumed segment
fetches, CRC re-verified, epoch-fenced; mxnet_tpu/fleet/replicate.py)
and promotes from that local replica when the primary's manifest goes
stale — surviving the death of the primary's machine *and* disk. If
the primary's own journal disk fails while it is serving, the router
enters degraded mode instead of dying: control-plane mutations return
503 + Retry-After, routed predict/generate traffic keeps flowing, and
the lease loop's journal probe exits degraded mode automatically once
the disk recovers — no restart.

Endpoints (see mxnet_tpu/fleet/router.py):
    POST /v1/predict             least-loaded over ready replicas
    POST /v1/generate            session-affine, cursor-migrated hops
    POST /fleet/register|heartbeat|deregister      (replica-facing)
    POST /admin/split|promote|canary|canary/report|drain
    GET  /fleet                  registry + splits + canaries snapshot
    GET  /metrics                federated Prometheus exposition
                                 (?format=prometheus / Accept: text/plain)
                                 or the JSON fleet snapshot
    GET  /journal/manifest|segment|snapshot    (replication-facing,
                                 epoch-stamped; consumed by
                                 --replicate-from standbys)
    GET  /healthz /readyz /livez

The router never runs model code or touches a device — replicas own
the accelerators. SIGINT/SIGTERM stops the listener; with a journal it
then compacts (fsync + snapshot) so the successor replays O(snapshot),
releases the lease, and dumps the final fleet snapshot. Replicas keep
serving and re-register with the next router incarnation on their own.
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import sys
import threading
import urllib.parse
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _seed_static(router, urls):
    """Best-effort: register already-running servers by their /info."""
    seeded = []
    for url in urls:
        url = url.rstrip("/")
        try:
            with urllib.request.urlopen(url + "/info", timeout=3.0) as r:
                info = json.loads(r.read().decode())
        except Exception as e:
            print("route: cannot seed %s: %s" % (url, e), file=sys.stderr)
            continue
        rid = "static-%s" % url.split("//", 1)[-1].replace(":", "-")
        router.registry.register({
            "id": rid, "url": url,
            "model": info.get("model") or "default",
            "version": info.get("version") or "0",
            "mode": info.get("mode", "predict"),
            "identity": info.get("identity"),
            "ready": bool(info.get("ready", True)),
            "reason": info.get("reason"),
            "spec": info.get("generate"),
            "static": True,   # no heartbeats — exempt from the sweep
        })
        seeded.append(rid)
    return seeded


def _parse_autoscale(spec):
    """One ``--autoscale`` value -> config dict. Accepts
    ``MODEL=ARGV_TEMPLATE`` or a JSON object
    ``{"model": ..., "argv": ..., "min": 1, "max": 3, ...policy
    overrides...}``. The argv template is shlex-split after
    substituting ``{replica_id}`` and ``{register_url}``."""
    spec = spec.strip()
    if spec.startswith("{"):
        cfg = json.loads(spec)
        if not cfg.get("model") or not cfg.get("argv"):
            raise ValueError(
                "--autoscale JSON needs 'model' and 'argv' keys")
        return cfg
    model, sep, argv = spec.partition("=")
    if not sep or not model.strip() or not argv.strip():
        raise ValueError(
            "--autoscale wants MODEL=ARGV_TEMPLATE or a JSON object, "
            "got %r" % spec)
    return {"model": model.strip(), "argv": argv}


def _start_autoscalers(router, register_url, specs):
    """Build the shared supervisor plus one Autoscaler per --autoscale
    entry; returns (supervisor, scalers)."""
    import shlex

    from mxnet_tpu.fleet import (AutoscalePolicy, Autoscaler,
                                 ReplicaSpec, ReplicaSupervisor)
    sup = ReplicaSupervisor()
    sup.start()
    scalers = []
    for cfg in specs:
        model = str(cfg["model"])
        argv_t = shlex.split(str(cfg["argv"]))
        pol = AutoscalePolicy(
            min_replicas=cfg.get("min"), max_replicas=cfg.get("max"),
            high_watermark_s=cfg.get("high_watermark_s"),
            low_watermark_s=cfg.get("low_watermark_s"),
            breach_rounds=cfg.get("breach_rounds"),
            cooldown_s=cfg.get("cooldown_s"),
            startup_cost_s=cfg.get("startup_cost_s"),
            interval_s=cfg.get("interval_s"))

        log_dir = cfg.get("log_dir")

        def factory(rid, _argv=argv_t, _model=model, _logs=log_dir):
            argv = [a.format(replica_id=rid, register_url=register_url,
                             model=_model) for a in _argv]
            log_path = (os.path.join(_logs, rid + ".log")
                        if _logs else None)
            return ReplicaSpec(rid, argv, max_restarts=2,
                               log_path=log_path)

        scalers.append(Autoscaler(router, sup, factory, model,
                                  policy=pol,
                                  scaler=cfg.get("scaler")).start())
    return sup, scalers


def _lease_loop(router, jdir, interval_s, compact_every, stop_evt):
    """Primary-side lease heartbeat + journal auto-compaction. The
    lease payload changes every beat (the counter), so the standby's
    content-change monitor keeps seeing progress without either side
    comparing wall clocks."""
    from mxnet_tpu.fleet.journal import write_lease
    beat = 0
    while not stop_evt.is_set():
        beat += 1
        try:
            write_lease(jdir, {"epoch": router.epoch, "pid": os.getpid(),
                               "url": router.address, "beat": beat})
        except OSError as e:
            print("route: lease write failed: %s" % e, file=sys.stderr)
        # degraded-mode recovery: probe the journal each beat so a
        # recovered disk exits degraded mode without a restart
        if router.journal_degraded and router.check_journal():
            print("route: journal recovered — leaving degraded mode",
                  file=sys.stderr)
        jr = router.journal
        if (jr is not None and compact_every > 0
                and not router.journal_degraded
                and jr.records_since_compact >= compact_every):
            try:
                jr.compact(router.export_state())
            except OSError as e:
                print("route: compaction failed: %s" % e, file=sys.stderr)
        stop_evt.wait(interval_s)


def _build_router(args, jdir):
    from mxnet_tpu.fleet import ReplicaRegistry, Router
    registry = ReplicaRegistry(
        heartbeat_timeout_s=args.heartbeat_timeout_s)
    if jdir is None:
        return Router(registry=registry, hop_tokens=args.hop_tokens)
    return Router.from_journal(jdir, registry=registry,
                               hop_tokens=args.hop_tokens)


def _standby_wait(args, jdir, lease_timeout_s, poll_s, done):
    """Follow the primary until it goes stale, then promote: full
    re-replay (the tailer/replicator is only a warm cache — the replay
    is what fixes the true durable seq), epoch bump, rebind. With
    ``--replicate-from`` the journal is streamed over HTTP into the
    local ``jdir`` and staleness is the replicated manifest's content
    (no shared lease file); otherwise the shared-directory tailer +
    lease monitor. Returns (router, front) or (None, None) if
    interrupted."""
    from mxnet_tpu.fleet import route_http
    from mxnet_tpu.fleet.journal import JournalTailer, LeaseMonitor
    repl = tailer = monitor = None
    banner = {"standby": True, "journal": jdir,
              "lease_timeout_s": lease_timeout_s}
    if getattr(args, "replicate_from", None):
        from mxnet_tpu.fleet import JournalReplicator
        repl = JournalReplicator(args.replicate_from, jdir,
                                 poll_s=poll_s)
        banner["replicate_from"] = repl.source_url
    else:
        tailer = JournalTailer(jdir, idle_cap_s=poll_s)
        monitor = LeaseMonitor(jdir)
    print(json.dumps(banner), flush=True)
    while not done.is_set():
        if repl is not None:
            repl.poll()
            state = repl.state
            stale = repl.expired(lease_timeout_s)
            # backoff while the source is down, burst while catching
            # up, the poll interval when idle (satellite: same shape
            # as the tailer's capped idle backoff)
            wait_s = max(0.01, repl.next_delay_s())
        else:
            tailer.poll()
            state = tailer.state
            stale = monitor.expired(lease_timeout_s)
            wait_s = max(0.01, tailer.next_delay_s())
        if stale:
            # where to take over: the address the dead primary
            # journaled (replicas + clients point there); CLI fallback
            addr = state.address
            if addr:
                u = urllib.parse.urlsplit(addr)
                host, port = u.hostname or args.host, u.port or args.port
            else:
                host, port = args.host, args.port
            # cheap probe before paying a replay: a wedged-but-alive
            # primary still owns the socket — connect succeeds, so
            # keep waiting instead of replaying once per poll
            try:
                socket.create_connection((host, port), 0.25).close()
                done.wait(poll_s)
                continue
            except OSError:
                pass        # nothing listening — take over
            router = _build_router(args, jdir)
            try:
                front = route_http(router, host, port,
                                   verbose=args.verbose)
            except OSError as e:
                # EADDRINUSE: the primary's socket is still bound —
                # it may merely be wedged, not dead. Keep waiting.
                print("route: standby cannot bind %s:%d (%s); waiting"
                      % (host, port, e), file=sys.stderr)
                router.journal.close()
                done.wait(poll_s)
                continue
            router.announce(front.address)
            info = {"promoted": True, "epoch": router.epoch,
                    "url": front.address,
                    "replay": router.replay_stats}
            if repl is not None:
                info["replication"] = repl.stats()
            print(json.dumps(info), flush=True)
            return router, front
        done.wait(wait_s)
    return None, None


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8090)
    p.add_argument("--replicas", default=None,
                   help="comma-separated replica base URLs to seed "
                        "statically from their /info (replicas started "
                        "with --register need nothing here)")
    p.add_argument("--hop-tokens", type=int, default=None,
                   help="max_new_tokens per forwarded generate hop "
                        "(default MXNET_FLEET_HOP_TOKENS); 0 forwards "
                        "the whole budget in one hop")
    p.add_argument("--heartbeat-timeout-s", type=float, default=None,
                   help="seconds without a heartbeat before a replica "
                        "is declared dead "
                        "(default MXNET_FLEET_HEARTBEAT_TIMEOUT_S)")
    p.add_argument("--journal", default=None, metavar="DIR",
                   help="write-ahead journal directory: replay on "
                        "start, log every mutation, refresh a lease "
                        "(enables HA; docs/fleet.md)")
    p.add_argument("--standby", action="store_true",
                   help="warm standby: tail --journal and promote when "
                        "the primary's lease expires")
    p.add_argument("--replicate-from", default=None, metavar="URL",
                   help="with --standby: stream the primary's journal "
                        "over its HTTP front end into the local "
                        "--journal DIR instead of tailing a shared "
                        "directory (promotes from the local replica "
                        "even if the primary's disk dies with it)")
    p.add_argument("--lease-interval-s", type=float, default=None,
                   help="primary lease refresh period "
                        "(default MXNET_FLEET_LEASE_INTERVAL_S)")
    p.add_argument("--lease-timeout-s", type=float, default=None,
                   help="standby promotion threshold "
                        "(default MXNET_FLEET_LEASE_TIMEOUT_S)")
    p.add_argument("--autoscale", action="append", default=None,
                   metavar="SPEC",
                   help="autoscale a model's replicas from demand: "
                        "MODEL=ARGV_TEMPLATE (the tools/serve.py "
                        "command to launch one replica; {replica_id} "
                        "and {register_url} are substituted) or a JSON "
                        "object with model/argv plus policy overrides "
                        "(min, max, high_watermark_s, low_watermark_s, "
                        "breach_rounds, cooldown_s, startup_cost_s, "
                        "interval_s). Repeatable, one scaler per "
                        "model; defaults come from MXNET_AUTOSCALE_*.")
    p.add_argument("--force-primary", action="store_true",
                   help="skip the live-lease startup guard (operator "
                        "override after verifying the old primary is "
                        "really gone)")
    p.add_argument("--verbose", action="store_true")
    args = p.parse_args()

    from mxnet_tpu.config import flags
    from mxnet_tpu.fleet import route_http
    from mxnet_tpu.fleet.journal import (lease_holder_alive,
                                         release_lease)

    jdir = args.journal
    if args.standby and jdir is None:
        p.error("--standby requires --journal DIR")
    if args.replicate_from and not args.standby:
        p.error("--replicate-from requires --standby")
    lease_interval_s = (args.lease_interval_s
                        if args.lease_interval_s is not None
                        else flags.fleet_lease_interval_s)
    lease_timeout_s = (args.lease_timeout_s
                       if args.lease_timeout_s is not None
                       else flags.fleet_lease_timeout_s)

    done = threading.Event()

    def _shutdown(signum, frame):
        done.set()

    signal.signal(signal.SIGINT, _shutdown)
    signal.signal(signal.SIGTERM, _shutdown)

    if args.standby:
        router, front = _standby_wait(args, jdir, lease_timeout_s,
                                      flags.fleet_standby_poll_s, done)
        if router is None:       # interrupted while still standby
            return
        seeded = []
    else:
        if jdir is not None and not args.force_primary and \
                lease_holder_alive(jdir, wait_s=1.5 * lease_interval_s):
            print(json.dumps({
                "error": "journal %r has a live lease holder — another "
                         "primary is running (use --force-primary to "
                         "override)" % jdir}), flush=True)
            sys.exit(2)
        router = _build_router(args, jdir)
        front = route_http(router, args.host, args.port,
                           verbose=args.verbose)
        router.announce(front.address)
        seeded = []
        if args.replicas:
            seeded = _seed_static(
                router, [u for u in args.replicas.split(",")
                         if u.strip()])
        banner = {"routing": True, "url": front.address,
                  "replicas": seeded,
                  "hop_tokens": router.hop_tokens,
                  "heartbeat_timeout_s":
                      router.registry.heartbeat_timeout_s}
        if jdir is not None:
            banner["journal"] = jdir
            banner["epoch"] = router.epoch
            banner["replay"] = router.replay_stats
        print(json.dumps(banner), flush=True)

    supervisor, scalers = None, []
    if args.autoscale:
        specs = [_parse_autoscale(s) for s in args.autoscale]
        supervisor, scalers = _start_autoscalers(
            router, front.address, specs)
        print(json.dumps({"autoscale": [s.snapshot() for s in scalers]}),
              flush=True)

    lease_stop = threading.Event()
    lease_thread = None
    if jdir is not None:
        lease_thread = threading.Thread(
            target=_lease_loop,
            args=(router, jdir, lease_interval_s,
                  flags.fleet_journal_compact_every, lease_stop),
            name="mxtpu-route-lease", daemon=True)
        lease_thread.start()

    done.wait()
    # scalers first (no launches during teardown), then the owned
    # replica processes (SIGTERM -> they deregister + drain while the
    # front end is still up), then the listener itself
    for s in scalers:
        s.stop()
    if supervisor is not None:
        supervisor.stop()
    front.stop()
    if lease_thread is not None:
        lease_stop.set()
        lease_thread.join(5.0)
    if router.journal is not None:
        # successor replays O(snapshot): fsync the tail, then snapshot
        # + truncate via checkpoint.py's temp+fsync+rename
        try:
            router.journal.compact(router.export_state())
        except OSError as e:
            print("route: final compaction failed: %s" % e,
                  file=sys.stderr)
        router.journal.close()
        release_lease(jdir)
    print(json.dumps(router.fleet_snapshot()), flush=True)


if __name__ == "__main__":
    main()
