"""Run a fleet router in front of ``tools/serve.py`` replicas.

    python tools/route.py --port 8090 [--verbose]

Replicas self-register: start each ``tools/serve.py`` with
``--register http://127.0.0.1:8090`` and it appears in the rotation as
soon as its warmup finishes (push registration + heartbeats; nothing to
configure here). ``--replicas url1,url2`` additionally seeds the
registry from running non-fleet servers by scraping their ``/info``;
static seeds send no heartbeats, so they are exempt from the staleness
sweep and trusted until a proxied request to them fails.

Endpoints (see mxnet_tpu/fleet/router.py):
    POST /v1/predict             least-loaded over ready replicas
    POST /v1/generate            session-affine, cursor-migrated hops
    POST /fleet/register|heartbeat|deregister      (replica-facing)
    POST /admin/split|promote|canary|canary/report|drain
    GET  /fleet                  registry + splits + canaries snapshot
    GET  /metrics                federated Prometheus exposition
                                 (?format=prometheus / Accept: text/plain)
                                 or the JSON fleet snapshot
    GET  /healthz /readyz /livez

The router never runs model code or touches a device — replicas own
the accelerators. SIGINT/SIGTERM stops the listener; replicas keep
serving and re-register with the next router incarnation on their own.
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _seed_static(router, urls):
    """Best-effort: register already-running servers by their /info."""
    seeded = []
    for url in urls:
        url = url.rstrip("/")
        try:
            with urllib.request.urlopen(url + "/info", timeout=3.0) as r:
                info = json.loads(r.read().decode())
        except Exception as e:
            print("route: cannot seed %s: %s" % (url, e), file=sys.stderr)
            continue
        rid = "static-%s" % url.split("//", 1)[-1].replace(":", "-")
        router.registry.register({
            "id": rid, "url": url,
            "model": info.get("model") or "default",
            "version": info.get("version") or "0",
            "mode": info.get("mode", "predict"),
            "identity": info.get("identity"),
            "ready": bool(info.get("ready", True)),
            "reason": info.get("reason"),
            "spec": info.get("generate"),
            "static": True,   # no heartbeats — exempt from the sweep
        })
        seeded.append(rid)
    return seeded


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8090)
    p.add_argument("--replicas", default=None,
                   help="comma-separated replica base URLs to seed "
                        "statically from their /info (replicas started "
                        "with --register need nothing here)")
    p.add_argument("--hop-tokens", type=int, default=None,
                   help="max_new_tokens per forwarded generate hop "
                        "(default MXNET_FLEET_HOP_TOKENS); 0 forwards "
                        "the whole budget in one hop")
    p.add_argument("--heartbeat-timeout-s", type=float, default=None,
                   help="seconds without a heartbeat before a replica "
                        "is declared dead "
                        "(default MXNET_FLEET_HEARTBEAT_TIMEOUT_S)")
    p.add_argument("--verbose", action="store_true")
    args = p.parse_args()

    from mxnet_tpu.fleet import ReplicaRegistry, Router, route_http

    registry = ReplicaRegistry(heartbeat_timeout_s=args.heartbeat_timeout_s)
    router = Router(registry=registry, hop_tokens=args.hop_tokens)
    seeded = []
    if args.replicas:
        seeded = _seed_static(
            router, [u for u in args.replicas.split(",") if u.strip()])
    front = route_http(router, args.host, args.port, verbose=args.verbose)
    banner = {"routing": True, "url": front.address,
              "replicas": seeded,
              "hop_tokens": router.hop_tokens,
              "heartbeat_timeout_s": registry.heartbeat_timeout_s}
    print(json.dumps(banner), flush=True)

    done = threading.Event()

    def _shutdown(signum, frame):
        done.set()

    signal.signal(signal.SIGINT, _shutdown)
    signal.signal(signal.SIGTERM, _shutdown)
    done.wait()
    front.stop()
    print(json.dumps(router.fleet_snapshot()), flush=True)


if __name__ == "__main__":
    main()
