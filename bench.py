"""Benchmark: ResNet-50 synthetic-data training throughput on one chip.

Mirrors the reference's `train_imagenet.py --benchmark 1` measurement
(reference docs/faq/perf.md:228-237; BASELINE.md). vs_baseline compares
against the reference's published V100 number at the same batch size:
363.69 img/s (batch 128, MXNet 1.2 + cuDNN, docs/faq/perf.md:237).

Methodology:
* master weights / optimizer state / BN stats in float32, compute in
  bfloat16 (mixed precision — the TPU analog of the reference's
  multi-precision fp16 path, docs/faq/perf.md:181-194);
* fresh PRNG key per step (folded), donated buffers, fused
  fwd+bwd+update in one XLA program;
* reports MFU = achieved FLOP/s / chip peak, with FLOPs taken from XLA's
  cost analysis of the compiled step (falling back to the analytic
  3 x 2 x 4.1 GFLOP/img ResNet-50 estimate).

Robustness: the TPU backend is probed in a subprocess with a timeout so a
wedged tunnel cannot hang the bench; on probe failure we pin the CPU
platform and mark the result `_CPU_FALLBACK`.

One JSON line on stdout: {"metric", "value", "unit", "vs_baseline", ...}.
"""
import json
import os
import subprocess
import sys
import time

BASELINE_IMG_S = 363.69  # V100 ResNet-50 train, batch 128 (perf.md:237)
RESNET50_TRAIN_FLOPS_PER_IMG = 3 * 2 * 4.089e9  # fwd+bwd ~= 3x fwd MACs*2

# bf16 peak FLOP/s per chip by device kind substring
_PEAK_FLOPS = [
    ("v6", 918e12), ("v5p", 459e12), ("v5", 197e12),  # v5 lite (v5e)
    ("v4", 275e12), ("v3", 123e12), ("v2", 45e12),
]


def _peak_flops(device_kind: str) -> float:
    kind = device_kind.lower()
    for sub, peak in _PEAK_FLOPS:
        if sub in kind:
            return peak
    return 197e12  # assume v5e


def probe_tpu(timeout: float) -> bool:
    """Check TPU liveness in a subprocess (a hung PJRT init can't be
    interrupted in-process)."""
    code = ("import jax; d = jax.devices(); "
            "assert d[0].platform != 'cpu'; print(d[0].device_kind)")
    try:
        r = subprocess.run([sys.executable, "-c", code], timeout=timeout,
                           capture_output=True, text=True)
        return r.returncode == 0
    except (subprocess.TimeoutExpired, OSError):
        return False


def main():
    probe_timeout = float(os.environ.get("BENCH_TPU_PROBE_TIMEOUT", "300"))
    want_cpu = os.environ.get("BENCH_PLATFORM", "") == "cpu"
    on_tpu = (not want_cpu) and probe_tpu(probe_timeout)

    import jax
    if not on_tpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    import mxnet_tpu  # noqa: F401
    from mxnet_tpu import models
    from mxnet_tpu.parallel import SPMDTrainStep, make_mesh

    devices = jax.devices()[:1]
    on_tpu = devices[0].platform != "cpu"
    batch = 128 if on_tpu else 8  # CPU fallback: smoke-size only

    sym = models.resnet_symbol(num_classes=1000, num_layers=50)
    arg_shapes, _, aux_shapes = sym.infer_shape(data=(batch, 3, 224, 224))
    arg_names = sym.list_arguments()
    aux_names = sym.list_auxiliary_states()
    param_shapes = {n: tuple(s) for n, s in zip(arg_names, arg_shapes)
                    if n not in ("data", "softmax_label")}
    aux_shapes_d = {n: tuple(s) for n, s in zip(aux_names, aux_shapes)}

    mesh = make_mesh({"dp": 1}, devices=devices)
    step = SPMDTrainStep(sym, mesh, lr=0.05, dtype=jnp.bfloat16)
    step.compile(param_shapes, aux_shapes_d,
                 {"data": (batch, 3, 224, 224)},
                 {"softmax_label": (batch,)})
    params, aux, opt = step.init(param_shapes, aux_shapes_d)

    rng = np.random.RandomState(0)
    data = {"data": jnp.asarray(rng.randn(batch, 3, 224, 224), jnp.bfloat16)}
    label = {"softmax_label": jnp.asarray(
        rng.randint(0, 1000, (batch,)), jnp.float32)}
    base_key = jax.random.PRNGKey(0)

    # FLOPs/step from XLA cost analysis of the compiled step
    flops_per_step = RESNET50_TRAIN_FLOPS_PER_IMG * batch
    try:
        cost = step._jitted.lower(
            params, aux, opt, data, label, base_key).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        if cost and cost.get("flops", 0) > 0:
            flops_per_step = float(cost["flops"])
    except Exception:
        pass

    def force(*arrays):
        # Forced HOST FETCH: device_get must materialize the bytes, so it
        # cannot return before every step in the dependency chain has run.
        # (round 2 used block_until_ready, which does not reliably block on
        # proxy/tunnel backends — it reported a physically impossible 661%
        # MFU. A host fetch is the ground truth.)
        vals = [np.asarray(jax.device_get(a)) for a in arrays]
        return float(vals[0].ravel()[0])

    # warmup (compile + settle)
    for i in range(3):
        key = jax.random.fold_in(base_key, i)
        params, aux, opt, outs = step(params, aux, opt, data, label, key)
    force(outs[0], next(iter(params.values())))

    n_steps = 30 if on_tpu else 3
    t0 = time.perf_counter()
    for i in range(n_steps):
        key = jax.random.fold_in(base_key, 100 + i)
        params, aux, opt, outs = step(params, aux, opt, data, label, key)
    # end timing on a host fetch of BOTH the last outputs and the updated
    # params: the params chain through every step, so this transitively
    # waits for all n_steps programs.
    force(outs[0], next(iter(params.values())))
    dt = time.perf_counter() - t0
    img_s = batch * n_steps / dt
    step_ms = dt / n_steps * 1e3

    # cross-check: fully synchronous per-step latency (fetch every step).
    # An async-dispatch bug shows up as sync_step_ms >> step_ms.
    n_sync = 5 if on_tpu else 1
    t1 = time.perf_counter()
    for i in range(n_sync):
        key = jax.random.fold_in(base_key, 200 + i)
        params, aux, opt, outs = step(params, aux, opt, data, label, key)
        force(outs[0])
    sync_step_ms = (time.perf_counter() - t1) / n_sync * 1e3

    mfu = 0.0
    if on_tpu:
        mfu = (img_s / batch) * flops_per_step / _peak_flops(
            devices[0].device_kind)
        # A broken harness must fail loudly, not record an impossible number
        # (raise, not assert: asserts vanish under python -O).
        if not 0.0 < mfu <= 1.0:
            raise RuntimeError(
                "measured MFU %.3f is outside (0, 1] — timing harness is not "
                "measuring execution (step_ms=%.2f sync_step_ms=%.2f)"
                % (mfu, step_ms, sync_step_ms))

    print(json.dumps({
        "metric": "resnet50_train_img_per_sec_b%d_bf16%s"
                  % (batch, "" if on_tpu else "_CPU_FALLBACK"),
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 3),
        "mfu": round(mfu, 4),
        "step_ms": round(step_ms, 3),
        "sync_step_ms": round(sync_step_ms, 3),
        "device": devices[0].device_kind,
        "flops_per_step": flops_per_step,
    }))


if __name__ == "__main__":
    main()
