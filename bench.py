"""Benchmark: ResNet-50 synthetic-data training throughput on one chip,
measured THROUGH the product API (`Module.fit`), not around it.

Mirrors the reference's `train_imagenet.py --benchmark 1` measurement
(reference docs/faq/perf.md:228-237; BASELINE.md). vs_baseline compares
against the reference's published V100 number at the same batch size:
363.69 img/s (batch 128, MXNet 1.2 + cuDNN, docs/faq/perf.md:237).

Methodology:
* `Module.fit(kvstore='tpu_sync', optimizer_params={'multi_precision':
  True})` — the fused one-XLA-program step (module/fused.py): fwd+bwd+
  optimizer update, f32 master weights, bf16 compute (the TPU analog of the
  reference's fp16 multi-precision path, docs/faq/perf.md:181-194);
* one device-resident synthetic batch repeated (the reference's
  --benchmark 1 semantics), `eval_metric=None` so no per-batch host sync;
* timing ends on a FORCED HOST FETCH of updated params (device_get):
  block_until_ready does not reliably block on proxy backends and round 2
  recorded an impossible number because of it; a fully-synchronous
  per-step cross-check is also reported;
* MFU = achieved FLOP/s / chip peak, FLOPs from XLA's cost analysis of the
  compiled fused step (fallback: analytic 3 x 2 x 4.1 GFLOP/img).

One JSON line on stdout: {"metric", "value", "unit", "vs_baseline", ...}.
"""
import json
import os
import subprocess
import sys
import time

BASELINE_IMG_S = 363.69  # V100 ResNet-50 train, batch 128 (perf.md:237)
RESNET50_TRAIN_FLOPS_PER_IMG = 3 * 2 * 4.089e9  # fwd+bwd ~= 3x fwd MACs*2

# bf16 peak FLOP/s per chip by device kind substring
_PEAK_FLOPS = [
    ("v6", 918e12), ("v5p", 459e12), ("v5", 197e12),  # v5 lite (v5e)
    ("v4", 275e12), ("v3", 123e12), ("v2", 45e12),
]


def _peak_flops(device_kind: str) -> float:
    kind = device_kind.lower()
    for sub, peak in _PEAK_FLOPS:
        if sub in kind:
            return peak
    return 197e12  # assume v5e


def probe_tpu(timeout: float) -> bool:
    """Check TPU liveness in a subprocess (a hung PJRT init can't be
    interrupted in-process)."""
    code = ("import jax; d = jax.devices(); "
            "assert d[0].platform != 'cpu'; print(d[0].device_kind)")
    try:
        r = subprocess.run([sys.executable, "-c", code], timeout=timeout,
                           capture_output=True, text=True)
        return r.returncode == 0
    except (subprocess.TimeoutExpired, OSError):
        return False


class _OneBatchIter:
    """Reference --benchmark 1 semantics: one device-resident batch,
    repeated; zero input-pipeline cost so the step program is what's
    measured."""

    def __init__(self, batch, steps, provide_data, provide_label):
        self._batch = batch
        self._steps = steps
        self.provide_data = provide_data
        self.provide_label = provide_label
        self.batch_size = provide_data[0].shape[0]
        self._i = 0

    def __iter__(self):
        return self

    def __next__(self):
        if self._i >= self._steps:
            raise StopIteration
        self._i += 1
        return self._batch

    def reset(self):
        self._i = 0


def main():
    probe_timeout = float(os.environ.get("BENCH_TPU_PROBE_TIMEOUT", "300"))
    want_cpu = os.environ.get("BENCH_PLATFORM", "") == "cpu"
    on_tpu = (not want_cpu) and probe_tpu(probe_timeout)

    import jax
    if not on_tpu:
        jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import models
    from mxnet_tpu.io import DataBatch, DataDesc

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    ctx = mx.tpu() if on_tpu else mx.cpu()
    batch = 128 if on_tpu else 8  # CPU fallback: smoke-size only
    steps = 30 if on_tpu else 3

    sym = models.resnet_symbol(num_classes=1000, num_layers=50)
    rng = np.random.RandomState(0)
    data_nd = mx.nd.array(rng.randn(batch, 3, 224, 224).astype(np.float32),
                          ctx=ctx)
    label_nd = mx.nd.array(rng.randint(0, 1000, (batch,)).astype(np.float32),
                           ctx=ctx)
    it = _OneBatchIter(
        DataBatch(data=[data_nd], label=[label_nd]), steps,
        [DataDesc("data", (batch, 3, 224, 224))],
        [DataDesc("softmax_label", (batch,))])

    mod = mx.mod.Module(sym, context=ctx)

    def force():
        # host fetch: cannot return before the whole dependency chain ran
        arr = mod._exec.arg_dict[mod._param_names[0]]._data
        return float(np.asarray(jax.device_get(arr)).ravel()[0])

    times = []

    def epoch_cb(epoch, symbol, arg_p, aux_p):
        force()
        times.append(time.perf_counter())

    # epoch 0 = warmup/compile; epochs 1..2 timed (through Module.fit)
    mod.fit(it, num_epoch=3, eval_metric=None, kvstore="tpu_sync",
            optimizer="sgd",
            optimizer_params={"learning_rate": 0.05, "momentum": 0.9,
                              "multi_precision": True},
            initializer=mx.initializer.Xavier(factor_type="in",
                                              magnitude=2.0),
            epoch_end_callback=epoch_cb)
    if mod._fused is None:
        raise RuntimeError("tpu_sync did not engage the fused train step — "
                           "bench would measure the eager path")
    dt = times[-1] - times[0]
    n_timed = steps * (len(times) - 1)
    img_s = batch * n_timed / dt
    step_ms = dt / n_timed * 1e3

    # cross-check: fully synchronous per-step latency (fetch every step).
    # An async-dispatch bug shows up as sync_step_ms >> step_ms.
    n_sync = 5 if on_tpu else 1
    batch_obj = it._batch
    t1 = time.perf_counter()
    for _ in range(n_sync):
        mod.forward_backward(batch_obj)
        mod.update()
        force()
    sync_step_ms = (time.perf_counter() - t1) / n_sync * 1e3

    # FLOPs/step from XLA cost analysis of the compiled fused program
    flops_per_step = RESNET50_TRAIN_FLOPS_PER_IMG * batch
    try:
        import jax.numpy as jnp
        ex = mod._exec
        fused = mod._fused
        npar = len(fused.param_names)
        lowered = fused._jitted.lower(
            ex._arg_vals(), ex._aux_vals(), mod._fused_opt_state,
            jnp.zeros((npar,), jnp.float32), jnp.zeros((npar,), jnp.float32),
            np.float32(1.0), np.int32(1), jax.random.PRNGKey(0))
        cost = lowered.compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        if cost and cost.get("flops", 0) > 0:
            flops_per_step = float(cost["flops"])
    except Exception:
        pass

    mfu = 0.0
    if on_tpu:
        mfu = (img_s / batch) * flops_per_step / _peak_flops(dev.device_kind)
        # A broken harness must fail loudly, not record an impossible number
        # (raise, not assert: asserts vanish under python -O).
        if not 0.0 < mfu <= 1.0:
            raise RuntimeError(
                "measured MFU %.3f is outside (0, 1] — timing harness is "
                "not measuring execution (step_ms=%.2f sync_step_ms=%.2f)"
                % (mfu, step_ms, sync_step_ms))

    print(json.dumps({
        "metric": "resnet50_module_fit_img_per_sec_b%d_bf16%s"
                  % (batch, "" if on_tpu else "_CPU_FALLBACK"),
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 3),
        "mfu": round(mfu, 4),
        "step_ms": round(step_ms, 3),
        "sync_step_ms": round(sync_step_ms, 3),
        "device": dev.device_kind,
        "flops_per_step": flops_per_step,
    }))


if __name__ == "__main__":
    main()
