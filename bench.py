"""Benchmark: ResNet-50 synthetic-data training throughput on one chip,
measured THROUGH the product API (`Module.fit`), not around it.

Mirrors the reference's `train_imagenet.py --benchmark 1` measurement
(reference docs/faq/perf.md:228-237; BASELINE.md). vs_baseline compares
against the reference's published V100 number at the same batch size:
363.69 img/s (batch 128, MXNet 1.2 + cuDNN, docs/faq/perf.md:237).

Methodology:
* `Module.fit(kvstore='tpu_sync', optimizer_params={'multi_precision':
  True})` — the fused one-XLA-program step (module/fused.py): fwd+bwd+
  optimizer update, f32 master weights, bf16 compute (the TPU analog of the
  reference's fp16 multi-precision path, docs/faq/perf.md:181-194);
* one device-resident synthetic batch repeated (the reference's
  --benchmark 1 semantics), `eval_metric=None` so no per-batch host sync;
* timing ends on a FORCED HOST FETCH of updated params (device_get):
  block_until_ready does not reliably block on proxy backends and round 2
  recorded an impossible number because of it; a fully-synchronous
  per-step cross-check is also reported;
* MFU = achieved FLOP/s / chip peak, FLOPs from XLA's cost analysis of the
  compiled fused step (fallback: analytic 3 x 2 x 4.1 GFLOP/img).

One JSON line on stdout: {"metric", "value", "unit", "vs_baseline", ...}.
"""
import json
import os
import subprocess
import sys
import time

BASELINE_IMG_S = 363.69  # V100 ResNet-50 train, batch 128 (perf.md:237)


def _perfmodel():
    # lazy: bench probes the TPU in a subprocess BEFORE touching anything
    # that imports jax in this process; mxnet_tpu.perfmodel itself is
    # jax-free but pulls in the package __init__
    from mxnet_tpu import perfmodel
    return perfmodel


def _peak_flops(device_kind: str) -> float:
    # shared with tools/microbench_convs.py and the kernel-tier cost
    # model (mxnet_tpu/tune/cost_model.py) via mxnet_tpu.perfmodel
    return _perfmodel().peak_flops(device_kind)


def probe_tpu(deadline_s: float, attempt_timeout: float) -> bool:
    """Retry TPU liveness probes (each in a subprocess — a hung PJRT init
    can't be interrupted in-process) until a hard wall-clock deadline.

    One timed-out attempt must NOT condemn the round to a CPU number: the
    tunnel has been observed to need several minutes after idle, and a
    killed probe process releases the relay so the next attempt can win.
    """
    code = ("import jax; d = jax.devices(); "
            "assert d[0].platform != 'cpu'; print(d[0].device_kind)")
    t_end = time.monotonic() + deadline_s
    attempt = 0
    while time.monotonic() < t_end:
        attempt += 1
        budget = min(attempt_timeout, max(30.0, t_end - time.monotonic()))
        try:
            r = subprocess.run([sys.executable, "-c", code], timeout=budget,
                               capture_output=True, text=True)
            if r.returncode == 0:
                return True
            if "AssertionError" in (r.stderr or ""):
                # jax initialized fine and resolved to CPU: there IS no
                # TPU on this host — deterministic, don't burn the
                # deadline retrying it
                print("bench: no TPU backend on this host (resolved to "
                      "CPU); not retrying", file=sys.stderr)
                return False
        except (subprocess.TimeoutExpired, OSError):
            pass
        print("bench: TPU probe attempt %d failed; %.0fs to deadline"
              % (attempt, max(0.0, t_end - time.monotonic())),
              file=sys.stderr)
        time.sleep(min(20.0, max(0.0, t_end - time.monotonic())))
    return False


_LAST_TPU_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "BENCH_LAST_TPU.json")


def _emit_stale_or_smoke():
    """The TPU never appeared. A CPU number must NEVER be the round's
    headline (round-3 lesson: a 0.39 img/s CPU line replaced the metric).
    Re-emit the last valid TPU result flagged stale — but with the
    chip-free secondary legs (kvstore roundtrip, LSTM tokens/s, dist kv)
    re-measured fresh on the host CPUs, so CPU-only rounds still track
    those regressions. Only if no TPU result has ever been recorded, emit
    an explicitly-labelled CPU smoke line."""
    if os.path.exists(_LAST_TPU_PATH):
        with open(_LAST_TPU_PATH) as f:
            last = json.load(f)
        last["stale"] = True
        last["stale_reason"] = ("TPU unreachable this run; value is the "
                                "last real-chip measurement")
        try:
            import jax
            jax.config.update("jax_platforms", "cpu")
            _secondary_legs(last, on_tpu=False)
            last["secondary_legs_platform"] = "cpu"
            last["secondary_legs_fresh"] = True
        except Exception as e:
            last["secondary_legs_fresh"] = "failed: %s" % e
        print(json.dumps(last))
        return True
    return False


def _secondary_legs(out, on_tpu):
    """The two other BASELINE.json metrics (kvstore push/pull µs, Gluon
    LSTM tokens/sec) plus the 2-process dist kv leg. None need the chip,
    so they are measured fresh even on CPU-only rounds."""
    try:
        from tools.bandwidth import measure as _kv_us
        out["kvstore_push_pull_us"] = _kv_us(
            "local", size_mb=1.0, reps=10 if on_tpu else 3)["value"]
    except Exception as e:
        out["kvstore_push_pull_us"] = "failed: %s" % e
    try:
        from tools.bench_lstm import measure as _lstm
        out["lstm_tokens_per_sec"] = _lstm(
            steps=10 if on_tpu else 2)["value"]
    except Exception as e:
        out["lstm_tokens_per_sec"] = "failed: %s" % e
    # dist leg: 2-process launch group on the host CPUs, so the µs
    # includes real cross-process serialization + TCP (the reference
    # measures tools/bandwidth/measure.py under a dmlc launch group)
    try:
        out["kvstore_dist_push_pull_us"] = _dist_kv_us()
    except Exception as e:
        out["kvstore_dist_push_pull_us"] = "failed: %s" % e
    # online-serving leg: dynamic-batch ResNet-50 artifact driven by the
    # closed-loop loadgen through mxnet_tpu.serve (BENCH_SERVING=0 skips)
    if os.environ.get("BENCH_SERVING", "1") == "1":
        try:
            out["serving"] = _serving_leg(on_tpu)
        except Exception as e:
            out["serving"] = "failed: %s" % e
    # continuous-batching decode leg: tokens/s goodput, TTFT/TPOT, and
    # the continuous-vs-static speedup on a ragged synthetic workload
    # (BENCH_DECODE=0 skips)
    if os.environ.get("BENCH_DECODE", "1") == "1":
        try:
            out["decode"] = _decode_leg(on_tpu)
        except Exception as e:
            out["decode"] = "failed: %s" % e
    # recommender leg: two-tower step time over the hot-row cache, the
    # sparse-vs-densified DDP comm ratio, and /v1/recommend goodput on
    # Zipf traffic (BENCH_RECO=0 skips)
    if os.environ.get("BENCH_RECO", "1") == "1":
        try:
            out["recommend"] = _reco_leg(on_tpu)
        except Exception as e:
            out["recommend"] = "failed: %s" % e
    # flash-attention kernel leg: chip-free tile pick + TPU-export custom
    # call census every round, wall microbench only on the chip
    # (BENCH_ATTN=0 skips)
    if os.environ.get("BENCH_ATTN", "1") == "1":
        try:
            out["attention"] = _attention_leg(on_tpu)
            kt = out.get("kernel_tier")
            if isinstance(kt, dict) and isinstance(out["attention"], dict):
                kt["flash_attn_custom_calls"] = \
                    out["attention"].get("census")
        except Exception as e:
            out["attention"] = "failed: %s" % e


def _reco_leg(on_tpu):
    """The PR-15 embedding subsystem end to end: train a pure-embedding
    two-tower model through the hot-row cache + spill store, report the
    per-step time and cache counters, the STATIC sparse-vs-densified
    gradient-exchange ratio (parallel/ddp.py sparse bucket kind — the
    >=10x headline), then export the towers as a format_version-6
    artifact and drive ``/v1/recommend`` with the Zipf closed loop.
    Runs the MXL511 chip-free gate over the served lookup."""
    import tempfile
    from functools import partial as _partial
    import numpy as np
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.embed import HotRowCache, SpillStore
    from mxnet_tpu.embed.serve import export_recommend
    from mxnet_tpu.parallel.ddp import SparseBucket
    from mxnet_tpu.serve import Server
    from tools.serve_loadgen import measure_recommend

    if on_tpu:
        U, I, D, B, steps, cap = 65536, 4096, 64, 512, 30, 8192
    else:
        U, I, D, B, steps, cap = 2048, 1024, 16, 128, 12, 384
    rng = np.random.RandomState(0)
    u_ids = ((rng.zipf(1.3, size=(steps, B)) - 1) % U).astype("int64")
    i_ids = rng.randint(0, I, size=(steps, B)).astype("int64")
    ratings = rng.randn(steps, B).astype("f4")
    lr = np.float32(0.1)

    store_u = SpillStore(U, D, seed=1)
    store_i = SpillStore(I, D, seed=2)
    cache_u = HotRowCache(store_u, cap)
    cache_i = HotRowCache(store_i, min(cap, I))

    @_partial(jax.jit, donate_argnums=(0, 1))
    def step(u_buf, i_buf, us, isl, r):
        uv, iv = u_buf[us], i_buf[isl]
        err = (uv * iv).sum(-1) - r
        d = (2.0 / r.shape[0]) * err
        gu = jnp.zeros_like(u_buf).at[us].add(d[:, None] * iv)
        gi = jnp.zeros_like(i_buf).at[isl].add(d[:, None] * uv)
        return u_buf - lr * gu, i_buf - lr * gi, (err ** 2).sum()

    # warm (compile + first fills), then time
    us, isl = cache_u.ensure(u_ids[0]), cache_i.ensure(i_ids[0])
    cache_u.buf, cache_i.buf, L = step(cache_u.buf, cache_i.buf, us,
                                       isl, jnp.asarray(ratings[0]))
    jax.block_until_ready(L)
    t0 = time.perf_counter()
    for s in range(1, steps):
        us, isl = cache_u.ensure(u_ids[s]), cache_i.ensure(i_ids[s])
        cache_u.buf, cache_i.buf, L = step(
            cache_u.buf, cache_i.buf, us, isl, jnp.asarray(ratings[s]))
        cache_u.note_updated(u_ids[s])
        cache_i.note_updated(i_ids[s])
    jax.block_until_ready(L)
    step_ms = (time.perf_counter() - t0) * 1e3 / (steps - 1)

    # static sparse-DDP exchange plan at a 4-rank mesh: what one step
    # moves coalesced (touched rows) vs densified (the whole table)
    ranks = 4
    plan = [SparseBucket("user", B // ranks, D, U),
            SparseBucket("item", B // ranks, D, I)]
    sparse_b = sum(sb.comm_bytes(ranks) for sb in plan)
    dense_b = sum(sb.densified_bytes() for sb in plan)

    cache_u.flush()
    cache_i.flush()
    art = tempfile.mktemp(suffix=".reco.mxtpu")
    export_recommend(store_u.peek(np.arange(U)),
                     store_i.peek(np.arange(I)), art,
                     max_ids=64, k=10)
    try:
        srv = Server(art, queue_depth=64)
        load = measure_recommend(
            srv, concurrency=8 if on_tpu else 4,
            requests=256 if on_tpu else 64, mean_ids=8, zipf=1.3)
        diags = srv.engine.check_discipline()
        srv.close(drain=True)
    finally:
        try:
            os.unlink(art)
        except OSError:
            pass
    return {
        "platform": "tpu" if on_tpu else "cpu_smoke",
        "table": "%dx%d + %dx%d" % (U, D, I, D),
        "cache_rows": cap,
        "train_step_ms": round(step_ms, 3),
        "train_cache": {k: cache_u.stats()[k] for k in
                        ("hit_rate", "evictions", "spill_bytes",
                         "upload_bytes")},
        "sparse_comm_bytes": sparse_b,
        "densified_comm_bytes": dense_b,
        "sparse_compression": round(dense_b / float(sparse_b), 1),
        "recommend_goodput_qps": load["goodput_qps"],
        "recommend_p50_ms": load["latency_ms"]["p50"],
        "recommend_p99_ms": load["latency_ms"]["p99"],
        "serve_cache_hit_rate": load.get("cache_hit_rate"),
        "mxl511": "clean" if not diags else [str(d) for d in diags],
    }


def _attention_leg(on_tpu):
    """Flash-attention kernel family microbench (kernels/attention.py).

    Chip-free on every round: the tuner's cost model picks the tile
    config for the benched shapes, and a TPU-platform ``jax.export``
    under ``tier.force_compiled()`` proves the custom calls survive
    into the cross-compiled program (``mxk_flash_attn`` /
    ``mxk_flash_attn_paged`` census — the numbers
    tests/test_attention_kernel.py pins). Wall timing of kernel vs the
    dense reference runs only on the chip: the CPU interpreter's wall
    time says nothing about Mosaic."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax import export as _export
    from mxnet_tpu import hlo_stats
    from mxnet_tpu.kernels import attention as _attn
    from mxnet_tpu.kernels import tier as _tier
    from mxnet_tpu.tune import tuner as _tuner

    if on_tpu:
        B, H, T, D = 4, 8, 1024, 64
        S, W, MP, page = 8, 4, 8, 16
    else:
        B, H, T, D = 1, 2, 128, 16
        S, W, MP, page = 2, 2, 2, 8
    leg = {"platform": "tpu" if on_tpu else "cpu_smoke",
           "train_shape": [B, H, T, D],
           "paged_geometry": {"slots": S, "window": W, "pages_per_slot": MP,
                              "page_size": page}}

    # chip-free tile pick for the benched shapes (docs/tuning.md): same
    # ranking tools/autotune.py --chip-free would commit
    shapes = _attn.shape_key_shapes((B, H, T, D), (B, H, T, D))
    res = _tuner.tune("flash_attn", shapes, "float32", chip_free=True)
    leg["config"] = dict(res["best"]["config"])
    leg["model_score_us"] = round(res["best"]["score_us"], 2)
    pshapes = _attn.paged_shape_key_shapes((S, W, H * D), H, page, (S, MP))
    pres = _tuner.tune("flash_attn_paged", pshapes, "float32",
                       chip_free=True)
    leg["paged_config"] = dict(pres["best"]["config"])
    leg["paged_model_score_us"] = round(pres["best"]["score_us"], 2)

    # TPU-platform export census under force_compiled: the kernels must
    # reach the lowered program even when exported from a chip-free host
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, H, T, D).astype("f4"))
    census = {}
    with _tier.force_compiled():
        exp = _export.export(
            jax.jit(lambda a, b_, c: _attn.flash_attention(
                a, b_, c, causal=True)), platforms=["tpu"])(q, q, q)
        for name, n in hlo_stats.pallas_kernel_names(
                exp.mlir_module()).items():
            census[name] = census.get(name, 0) + n
        kv = jnp.zeros(((S * MP + 1) * page, H * D), jnp.float32)
        pq = jnp.asarray(rng.randn(S, W, H * D).astype("f4"))
        bt = jnp.asarray(
            (1 + np.arange(S * MP, dtype=np.int32)).reshape(S, MP))
        pos = jnp.full((S,), page * MP - W, jnp.int32)
        pexp = _export.export(
            jax.jit(lambda a, kp, vp, b_, p_: _attn.paged_attention(
                a, kp, vp, b_, p_, heads=H, page_size=page)),
            platforms=["tpu"])(pq, kv, kv, bt, pos)
        for name, n in hlo_stats.pallas_kernel_names(
                pexp.mlir_module()).items():
            census[name] = census.get(name, 0) + n
    leg["census"] = census

    if on_tpu:
        def _time_us(fn, *args, iters=10):
            out = jax.block_until_ready(fn(*args))
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                for _ in range(iters):
                    out = fn(*args)
                jax.block_until_ready(out)
                best = min(best, (time.perf_counter() - t0) * 1e6 / iters)
            return best
        kern = jax.jit(lambda a, b_, c: _attn.flash_attention(
            a, b_, c, causal=True, config=leg["config"]))
        ref = jax.jit(lambda a, b_, c: _attn.reference_attention(
            a, b_, c, causal=True))
        leg["kernel_us"] = round(_time_us(kern, q, q, q), 1)
        leg["reference_us"] = round(_time_us(ref, q, q, q), 1)
        leg["speedup"] = round(leg["reference_us"]
                               / max(leg["kernel_us"], 1e-9), 2)
    return leg


def _decode_leg(on_tpu):
    """Autoregressive decode through the continuous-batching engine
    (serve/decode.py): export ONE generate artifact, then run the same
    ragged workload — per group of ``max_slots`` requests, all but one
    want a handful of tokens and one wants a long completion — in
    continuous mode (finished slots refill between decode steps) and in
    static mode (a group runs to its last straggler). The headline is
    the goodput ratio; decode STEP counts are reported too since they
    are the deterministic, load-independent form of the same ratio.
    A second pass runs the same workload through a speculative
    (int8-draft, format_version-5) artifact with speculation on vs off
    at matched distribution, reporting accepted-tokens/step, draft
    acceptance rate, and the tokens/s/user + step-count speedups.
    Runs the MXL508 and MXL510 chip-free gates over the served steps."""
    import tempfile
    import numpy as np
    from mxnet_tpu import serving
    from mxnet_tpu.serve import GenerateSession
    from mxnet_tpu.serve import decode_model as _dm

    if on_tpu:
        spec = _dm.DecoderSpec(vocab=512, dim=256, num_heads=8,
                               num_layers=4, max_prompt_len=16,
                               page_size=16, max_pages_per_slot=8,
                               max_slots=16, num_pages=160)
        short_new, long_new, groups = 4, 108, 3
    else:
        spec = _dm.DecoderSpec(vocab=128, dim=64, num_heads=4,
                               num_layers=2, max_prompt_len=8,
                               page_size=8, max_pages_per_slot=6,
                               max_slots=8, num_pages=64)
        short_new, long_new, groups = 2, 40, 3
    params = _dm.init_params(spec, seed=0)
    art = tempfile.mktemp(suffix=".gen.mxtpu")
    t0 = time.perf_counter()
    serving.export_generate(params, spec, art)
    leg = {"platform": "tpu" if on_tpu else "cpu_smoke",
           "model": "gpt_d%d_l%d" % (spec.dim, spec.num_layers),
           "export_s": round(time.perf_counter() - t0, 2),
           "artifact_mb": round(os.path.getsize(art) / 1e6, 1),
           "slots": spec.max_slots, "page_size": spec.page_size,
           "kv_pages": spec.num_pages - 1}

    rng = np.random.RandomState(0)
    S = spec.max_slots
    work = []   # (prompt, max_new)
    for _ in range(groups):
        for j in range(S):
            plen = int(rng.randint(2, spec.max_prompt_len + 1))
            prompt = rng.randint(2, spec.vocab, size=plen).tolist()
            work.append((prompt, long_new if j == S - 1 else short_new))

    def run_mode(continuous, path=art, **skw):
        sess = GenerateSession(path, auto_start=False,
                               continuous=continuous, timeout_ms=0,
                               queue_depth=len(work) + 1, **skw)
        t1 = time.perf_counter()
        reqs = [sess.submit(p, max_new_tokens=n, temperature=0.0, seed=0)
                for p, n in work]
        rounds = 0
        cap = sum(n for _, n in work) * 4 + 64
        while not all(r.done() for r in reqs) and rounds < cap:
            sess.run_round()
            rounds += 1
        wall = time.perf_counter() - t1
        outs = [r.result(timeout=1.0) for r in reqs]
        toks = sum(len(o["tokens"]) for o in outs)
        ttfts = sorted(o["ttft_ms"] for o in outs)
        tpots = sorted(o["tpot_ms"] for o in outs
                       if o["tpot_ms"] is not None)
        sess._publish_window(force=True)
        snap = sess.metrics_.snapshot()
        steps = snap["decode_steps"]
        diags = (sess.check_discipline()
                 + sess.check_speculative_discipline()) \
            if continuous else []
        mxl512 = None
        if continuous:
            from mxnet_tpu.kernels import tier as _ktier
            if _ktier.tier() != "off":
                a = sess.check_attention_discipline()
                mxl512 = "clean" if not a else [str(d) for d in a]
        sess.close(drain=True)

        def pct(xs, q):
            return round(xs[min(len(xs) - 1,
                                int(q / 100.0 * len(xs)))], 3) \
                if xs else None
        res = {"tokens": toks, "wall_s": round(wall, 3),
               "tokens_per_s": round(toks / wall, 1),
               "decode_steps": steps,
               "ttft_ms_p50": pct(ttfts, 50),
               "ttft_ms_p99": pct(ttfts, 99),
               "tpot_ms_p50": pct(tpots, 50),
               "tpot_ms_p99": pct(tpots, 99)}
        sp = snap.get("speculative")
        if sp and sp.get("steps"):
            res["accepted_tokens_per_step"] = sp["accepted_tokens_per_step"]
            res["draft_acceptance_rate"] = sp["draft_acceptance_rate"]
        if mxl512 is not None:
            res["mxl512"] = mxl512
        return res, diags, [o["tokens"] for o in outs]

    # speculative leg: the SAME workload through a format_version-5
    # artifact bundling the int8 draft, speculation on vs off. Greedy
    # decode makes the comparison matched-distribution by construction
    # (the token streams are asserted identical); the step ratio is the
    # deterministic, load-independent form of the tokens/s/user speedup.
    draft = _dm.quantize_decoder_params(params)
    art5 = tempfile.mktemp(suffix=".spec.mxtpu")
    t0 = time.perf_counter()
    # k=4 rather than the roofline suggestion: these bench models are
    # far below the memory-bound regime the roofline models, and the
    # headline (step-count ratio at matched distribution) needs a
    # window deep enough for the acceptance tail to show
    serving.export_generate(params, spec, art5, draft_params=draft,
                            speculate_k=4)
    export5_s = round(time.perf_counter() - t0, 2)

    try:
        cont, diags, _ = run_mode(True)
        stat, _, _ = run_mode(False)
        # kernel on/off re-emit: the SAME continuous workload with the
        # Pallas attention tier forced auto vs off. The tier is resolved
        # when the decode module is LOWERED, so each arm exports its own
        # artifact under the override. Greedy decode pins the token
        # streams bitwise-equal (the kernel parity bar); the wall ratio
        # on a CPU round is the chip-free (interpreter) form of the
        # number — only the on-chip ratio is a performance claim.
        from mxnet_tpu.config import flags as _flags
        prev_tier = _flags.kernel_tier
        arts = {"auto": tempfile.mktemp(suffix=".kon.mxtpu"),
                "off": tempfile.mktemp(suffix=".koff.mxtpu")}
        try:
            _flags.set("kernel_tier", "auto")
            serving.export_generate(params, spec, arts["auto"])
            kern_on, _, ktoks_on = run_mode(True, path=arts["auto"])
            _flags.set("kernel_tier", "off")
            serving.export_generate(params, spec, arts["off"])
            kern_off, _, ktoks_off = run_mode(True, path=arts["off"])
        finally:
            _flags.set("kernel_tier", prev_tier)
            for f in arts.values():
                try:
                    os.unlink(f)
                except OSError:
                    pass
        spec_on, diags510, toks_on = run_mode(True, path=art5,
                                              speculative=True)
        spec_off, _, toks_off = run_mode(True, path=art5,
                                         speculative=False)
    finally:
        for f in (art, art5):
            try:
                os.unlink(f)
            except OSError:
                pass
    leg["continuous"] = cont
    leg["static"] = stat
    leg["speedup_tokens_per_s"] = round(
        cont["tokens_per_s"] / stat["tokens_per_s"], 2) \
        if stat["tokens_per_s"] else None
    leg["speedup_steps"] = round(
        stat["decode_steps"] / float(cont["decode_steps"]), 2) \
        if cont["decode_steps"] else None
    leg["mxl508"] = "clean" if not diags else [str(d) for d in diags]
    leg["kernel_on"] = kern_on
    leg["kernel_off"] = kern_off
    leg["kernel_tokens_matched"] = ktoks_on == ktoks_off
    leg["kernel_wall_ratio"] = round(
        kern_off["wall_s"] / kern_on["wall_s"], 2) \
        if kern_on["wall_s"] else None
    # the perfmodel policy's chosen depth next to the measured
    # acceptance, so the suggest_speculation_depth heuristic is
    # auditable against what the chip actually accepted
    spec_on["policy_k"] = _dm.suggest_speculation_depth(spec)
    spec_on["export_s"] = export5_s
    leg["speculative"] = spec_on
    leg["speculative_baseline"] = spec_off
    leg["speculative_matched"] = toks_on == toks_off
    leg["speculative_speedup_tokens_per_s_user"] = round(
        spec_on["tokens_per_s"] / spec_off["tokens_per_s"], 2) \
        if spec_off["tokens_per_s"] else None
    leg["speculative_speedup_steps"] = round(
        spec_off["decode_steps"] / float(spec_on["decode_steps"]), 2) \
        if spec_on["decode_steps"] else None
    leg["mxl510"] = "clean" if not diags510 else [str(d) for d in diags510]
    return leg


def _serving_leg(on_tpu):
    """ResNet-50 through the online serving runtime: export ONE
    dynamic-batch artifact, then for each batch bucket run a dedicated
    single-bucket server under the closed-loop load generator
    (tools/serve_loadgen.py, concurrency = bucket) and report p50/p99
    latency, goodput and padding-waste. Buckets {1, 8, 32} on the chip;
    a shrunken smoke (64x64 input, buckets {1, 8}) on CPU rounds so the
    serving path itself is regression-tracked every round."""
    import tempfile
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import models
    from mxnet_tpu.serve import Server
    from tools.serve_loadgen import measure

    side = 224 if on_tpu else 64
    classes = 1000 if on_tpu else 10
    buckets = (1, 8, 32) if on_tpu else (1, 8)
    reqs_per_bucket = 8 if on_tpu else 4

    sym = models.resnet_symbol(num_classes=classes, num_layers=50,
                               image_shape="3,%d,%d" % (side, side))
    shapes, _, aux_shapes = sym.infer_shape(data=(2, 3, side, side))
    rng = np.random.RandomState(0)
    args = {n: mx.nd.array(rng.uniform(-0.05, 0.05, s).astype("f4"))
            for n, s in zip(sym.list_arguments(), shapes)
            if n not in ("data", "softmax_label")}
    aux = {n: mx.nd.array(np.ones(s, "f4") if "var" in n
                          else np.zeros(s, "f4"))
           for n, s in zip(sym.list_auxiliary_states(), aux_shapes)}
    art = tempfile.mktemp(suffix=".mxtpu")
    t0 = time.perf_counter()
    mx.serving.export_compiled(sym, args, aux,
                               {"data": (None, 3, side, side)}, art)
    leg = {"platform": "tpu" if on_tpu else "cpu_smoke",
           "model": "resnet50_%dx%d" % (side, side),
           "export_s": round(time.perf_counter() - t0, 2),
           "artifact_mb": round(os.path.getsize(art) / 1e6, 1),
           "buckets": {}}
    try:
        for b in buckets:
            srv = Server(art, buckets=(b,), batch_timeout_ms=2)
            t1 = time.perf_counter()
            # pre-build the bucket engine: compile+warmup must not
            # pollute the latency percentiles (one-time cost, reported
            # separately)
            srv.model.engine_cache.engine(b)
            compile_s = time.perf_counter() - t1
            res = measure(srv, concurrency=b,
                          requests=reqs_per_bucket * b,
                          timeout_ms=600000)
            snap = srv.metrics()["buckets"].get(str(b), {})
            srv.close(drain=True)
            leg["buckets"][str(b)] = {
                "p50_ms": round(res["latency_ms"]["p50"], 2),
                "p99_ms": round(res["latency_ms"]["p99"], 2),
                "goodput_qps": res["goodput_qps"],
                "padding_waste": snap.get("padding_waste"),
                "occupancy": snap.get("occupancy"),
                "batches": snap.get("batches"),
                "engine_compile_s": round(compile_s, 2),
                "completed": res["completed"],
                "errors": res["errors"],
            }
        # int8 leg: quantize the SAME model (format_version 4 artifact),
        # serve it side-by-side with the f32 engines through the
        # dtype-routed bucket cache, and gate each bucket's top-1 delta
        # on flags.quant_accuracy_budget (BENCH_QUANT=0 skips)
        if os.environ.get("BENCH_QUANT", "1") == "1":
            try:
                leg["quant"] = _quant_serving_leg(
                    art, sym, args, aux, side, buckets, reqs_per_bucket)
            except Exception as e:
                leg["quant"] = "failed: %s" % e
    finally:
        try:
            os.unlink(art)
        except OSError:
            pass
    return leg


def _quant_serving_leg(f32_art, sym, args, aux, side, buckets,
                       reqs_per_bucket):
    """Int8 post-training quantization leg of the serving benchmark.

    Calibrates on deterministic synthetic batches, freezes a
    ``format_version`` 4 artifact (tools/quantize_model.py is the same
    path as a CLI), then for every bucket runs ONE server holding the
    f32 and int8 engines side-by-side: the loadgen drives the int8
    engines (``dtype="int8"``), and the accuracy probe replays an
    identical probe set through BOTH engine families at the bucket's
    batch size so the reported top-1 delta is per-bucket (it sees that
    bucket's padding). The probe numbers are already host-side, so the
    ``quant/accuracy_delta`` gauge costs zero extra device syncs."""
    import tempfile
    import numpy as np
    from mxnet_tpu import quant, telemetry as _telemetry
    from mxnet_tpu.config import flags as _flags
    from mxnet_tpu.serve import Server
    from tools.serve_loadgen import measure, measure_accuracy

    rng = np.random.RandomState(1)
    calib = [{"data": rng.randn(8, 3, side, side).astype("f4")}
             for _ in range(4)]
    q_art = tempfile.mktemp(suffix=".int8.mxtpu")
    t0 = time.perf_counter()
    meta = quant.export_quantized(sym, args, aux, calib,
                                  {"data": (None, 3, side, side)}, q_art)
    rep = meta["quant"]
    wb = rep["weight_bytes"]
    out = {"export_s": round(time.perf_counter() - t0, 2),
           "artifact_bytes_f32": os.path.getsize(f32_art),
           "artifact_bytes_int8": os.path.getsize(q_art),
           "weight_payload_ratio": round(wb["int8"] / float(wb["f32"]), 3)
           if wb["f32"] else None,
           "sites": len(rep["sites"]),
           "skipped": len(rep["skipped"]),
           "calibration_fingerprint": rep["calibration"]["fingerprint"],
           "accuracy_budget": float(_flags.quant_accuracy_budget),
           "buckets": {}}
    gauge = _telemetry.gauge(
        "quant/accuracy_delta",
        "top-1 accuracy delta (f32 - int8) of the quantized serving "
        "engines on the bench probe set, labelled by bucket")
    try:
        for b in buckets:
            srv = Server(f32_art, quantized=q_art, buckets=(b,),
                         batch_timeout_ms=2)
            t1 = time.perf_counter()
            srv.model.engine_cache.engine(b, dtype="int8")
            compile_s = time.perf_counter() - t1
            # the probe replays through BOTH engine families; build the
            # f32 sibling up front too so compiles stay out of every
            # latency number
            srv.model.engine_cache.engine(b, dtype="f32")
            res = measure(srv, concurrency=b,
                          requests=reqs_per_bucket * b,
                          timeout_ms=600000, dtype="int8")
            probe = measure_accuracy(srv, srv, examples=4 * b, batch=b)
            snap = (srv.metrics().get("buckets_by_dtype", {})
                    .get("int8", {}).get(str(b), {}))
            srv.close(drain=True)
            delta = probe["top1_delta"]
            gauge.set(delta, bucket=str(b))
            out["buckets"][str(b)] = {
                "p50_ms": round(res["latency_ms"]["p50"], 2),
                "p99_ms": round(res["latency_ms"]["p99"], 2),
                "goodput_qps": res["goodput_qps"],
                "padding_waste": snap.get("padding_waste"),
                "batches": snap.get("batches"),
                "engine_compile_s": round(compile_s, 2),
                "completed": res["completed"],
                "errors": res["errors"],
                "top1_delta": delta,
                "agreement": probe["agreement"],
                "accuracy_ok": delta <= float(_flags.quant_accuracy_budget),
            }
    finally:
        try:
            os.unlink(q_art)
        except OSError:
            pass
    return out


def _make_rec(n_images, side, path="/tmp/mxtpu_bench_%d_%d.rec"):
    """Generate (once, cached) a synthetic-ImageNet .rec of JPEG noise."""
    import cv2
    import numpy as np
    from mxnet_tpu import recordio
    path = path % (n_images, side)
    idx = os.path.splitext(path)[0] + ".idx"
    if os.path.exists(path) and os.path.exists(idx):
        return path
    rng = np.random.RandomState(0)
    w = recordio.MXIndexedRecordIO(idx, path, "w")
    for i in range(n_images):
        img = rng.randint(0, 255, (side, side, 3), dtype=np.uint8)
        ok, buf = cv2.imencode(".jpg", img, [cv2.IMWRITE_JPEG_QUALITY, 95])
        w.write_idx(i, recordio.pack(
            recordio.IRHeader(0, float(i % 1000), i, 0), buf.tobytes()))
    w.close()
    return path


def _data_leg(ctx, batch, n_images=512, side=144, shards=4):
    """Streaming data tier throughput (docs/data.md): decode+augment
    delivery rate of StreamingDataIter over a make_recordio-packed
    synthetic shard set. Host-side only — batches are consumed, never
    shipped to the device — so the number is pipeline rate, not link
    rate."""
    import numpy as np
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tools"))
    try:
        from make_recordio import iter_synth_images, shard_paths, \
            write_shards
    finally:
        sys.path.pop(0)
    from mxnet_tpu.data import (ImageDecoder, ShardedRecordStream,
                                StreamingDataIter)
    prefix = "/tmp/mxtpu_bench_data/synth_%d_%d" % (n_images, side)
    recs = shard_paths(prefix, shards)
    if not all(os.path.exists(r) for r in recs):
        recs = write_shards(
            iter_synth_images(n_images, side=side), prefix, shards)
    stream = ShardedRecordStream(recs, shuffle=True, seed=0)
    it = StreamingDataIter(
        stream, ImageDecoder((3, 128, 128), rand_crop=True,
                             rand_mirror=True),
        batch_size=batch, ctx=ctx)
    try:
        # warm epoch: thread spin-up + page cache, then the timed one
        for _ in it:
            pass
        it.reset()
        n = 0
        t0 = time.perf_counter()
        for b in it:
            n += b.data[0].shape[0]
        dt = time.perf_counter() - t0
        depth = it.queue_depth() if hasattr(it, "queue_depth") else None
        return {
            "examples_per_s": round(n / dt, 1),
            "records": stream.records_per_epoch(),
            "shards": len(recs),
            "decode_threads": it._nthreads,
            "queue_depth": depth,
        }
    finally:
        it.close()


class _OneBatchIter:
    """Reference --benchmark 1 semantics: one device-resident batch,
    repeated; zero input-pipeline cost so the step program is what's
    measured."""

    def __init__(self, batch, steps, provide_data, provide_label):
        self._batch = batch
        self._steps = steps
        self.provide_data = provide_data
        self.provide_label = provide_label
        self.batch_size = provide_data[0].shape[0]
        self._i = 0

    def __iter__(self):
        return self

    def __next__(self):
        if self._i >= self._steps:
            raise StopIteration
        self._i += 1
        return self._batch

    def reset(self):
        self._i = 0


def _dist_kv_us(n=2, size_mb=1.0):
    """kvstore push/pull µs with a REAL network leg: a 2-process
    tools/launch.py group on host CPUs (label: kv_type=dist_sync)."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    here = os.path.dirname(os.path.abspath(__file__))
    r = subprocess.run(
        [sys.executable, os.path.join(here, "tools", "launch.py"),
         "-n", str(n), sys.executable,
         os.path.join(here, "tools", "bandwidth.py"),
         "--kv-type", "dist_sync", "--platform", "cpu",
         "--size-mb", str(size_mb)],
        capture_output=True, text=True, timeout=600, env=env, cwd=here)
    vals = []
    for line in r.stdout.splitlines():
        _, _, payload = line.partition("{")
        if '"kvstore_push_pull_us"' in line:
            vals.append(json.loads("{" + payload)["value"])
    if not vals:
        raise RuntimeError("no worker reported: %s" % r.stdout[-500:])
    return round(sum(vals) / len(vals), 1)


def main():
    # generous defaults: the tunnel can take minutes to come up after idle;
    # falling back to CPU on a slow-but-alive TPU would record a misleading
    # number, so we retry probes until a hard deadline
    probe_timeout = float(os.environ.get("BENCH_TPU_PROBE_TIMEOUT", "540"))
    probe_deadline = float(os.environ.get("BENCH_TPU_DEADLINE", "1500"))
    want_cpu = os.environ.get("BENCH_PLATFORM", "") == "cpu"
    on_tpu = (not want_cpu) and probe_tpu(probe_deadline, probe_timeout)

    if not on_tpu and not want_cpu and _emit_stale_or_smoke():
        return

    import jax
    if not on_tpu:
        jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import models
    from mxnet_tpu.config import flags as _flags
    from mxnet_tpu.io import DataBatch, DataDesc

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    ctx = mx.tpu() if on_tpu else mx.cpu()
    batch = 128 if on_tpu else 8  # CPU fallback: smoke-size only
    steps = 30 if on_tpu else 3

    sym = models.resnet_symbol(num_classes=1000, num_layers=50)
    rng = np.random.RandomState(0)
    data_nd = mx.nd.array(rng.randn(batch, 3, 224, 224).astype(np.float32),
                          ctx=ctx)
    label_nd = mx.nd.array(rng.randint(0, 1000, (batch,)).astype(np.float32),
                           ctx=ctx)
    it = _OneBatchIter(
        DataBatch(data=[data_nd], label=[label_nd]), steps,
        [DataDesc("data", (batch, 3, 224, 224))],
        [DataDesc("softmax_label", (batch,))])

    mod = mx.mod.Module(sym, context=ctx)

    def force():
        # host fetch: cannot return before the whole dependency chain ran
        arr = mod._exec.arg_dict[mod._param_names[0]]._data
        return float(np.asarray(jax.device_get(arr)).ravel()[0])

    def timing_cb(lst):
        # epoch-end probe shared by every measured fit(): force a host
        # fetch (the only reliable sync on proxy backends), then stamp
        def cb(epoch, symbol, arg_p, aux_p):
            force()
            lst.append(time.perf_counter())
        return cb

    # seed the run-wide telemetry registry (docs/observability.md): with
    # flops known, fit()'s window sampling publishes a live train/mfu
    # gauge; the analytic estimate is refined from XLA cost analysis below
    from mxnet_tpu import telemetry as _telemetry
    _telemetry.set_run_info(
        flops_per_step=_perfmodel().RESNET50_TRAIN_FLOPS_PER_IMG * batch,
        device_kind=dev.device_kind, batch_size=batch)

    times = []
    epoch_cb = timing_cb(times)

    # epoch 0 = warmup/compile; epochs 1..2 timed (through Module.fit).
    # steps_per_dispatch=1 pins the per-step-dispatch headline (fit's
    # default of None would auto-engage the K-step scan here and fold the
    # grouped_* leg into the headline): the headline must keep matching
    # the reference's --benchmark 1 per-step semantics.
    mod.fit(it, num_epoch=3, eval_metric=None, kvstore="tpu_sync",
            optimizer="sgd",
            optimizer_params={"learning_rate": 0.05, "momentum": 0.9,
                              "multi_precision": True},
            initializer=mx.initializer.Xavier(factor_type="in",
                                              magnitude=2.0),
            steps_per_dispatch=1,
            epoch_end_callback=epoch_cb)
    if mod._fused is None:
        raise RuntimeError("tpu_sync did not engage the fused train step — "
                           "bench would measure the eager path")
    dt = times[-1] - times[0]
    n_timed = steps * (len(times) - 1)
    img_s = batch * n_timed / dt
    step_ms = dt / n_timed * 1e3

    # cross-check: fully synchronous per-step latency (fetch every step).
    # An async-dispatch bug shows up as sync_step_ms >> step_ms.
    n_sync = 5 if on_tpu else 1
    batch_obj = it._batch
    t1 = time.perf_counter()
    for _ in range(n_sync):
        # same donating program fit() used (a bare forward_backward would
        # trigger a second multi-minute XLA compile of the non-donating
        # variant for no measurement benefit)
        mod._fit_step(batch_obj)
        force()
    sync_step_ms = (time.perf_counter() - t1) / n_sync * 1e3

    # grouped dispatch (fit(steps_per_dispatch=K)): K fused steps ride ONE
    # XLA program (lax.scan over stacked batches), amortising per-dispatch
    # host/PJRT latency — which behind this environment's tunneled chip is
    # a large, hardware-irrelevant cost. ON BY DEFAULT (K=30 on the chip,
    # per the round-5 decomposition; a small K on CPU keeps the scan path
    # exercised every round): the dispatch-amortised numbers ride as the
    # grouped_* fields while the headline stays the per-step-dispatch fit,
    # matching the reference's --benchmark 1 semantics. BENCH_K=0 opts out.
    k_disp = int(os.environ.get("BENCH_K", "30" if on_tpu else "2"))
    grouped_img_s = grouped_step_ms = grouped_mfu = None
    if k_disp > 1:
        t_k = []
        it.reset()
        # continues on the already-initialized module; epoch 0 compiles
        # the scan program, epochs 1..2 are timed
        mod.fit(it, num_epoch=3, eval_metric=None, kvstore="tpu_sync",
                optimizer="sgd",
                optimizer_params={"learning_rate": 0.05, "momentum": 0.9,
                                  "multi_precision": True},
                steps_per_dispatch=k_disp,
                epoch_end_callback=timing_cb(t_k))
        dt_k = t_k[-1] - t_k[0]
        n_timed_k = steps * (len(t_k) - 1)
        grouped_img_s = batch * n_timed_k / dt_k
        grouped_step_ms = dt_k / n_timed_k * 1e3

    # FLOPs/step from XLA cost analysis of the compiled fused program
    flops_per_step = _perfmodel().RESNET50_TRAIN_FLOPS_PER_IMG * batch
    try:
        ex = mod._exec
        cost = mod._fused.cost_analysis(ex._arg_vals(), ex._aux_vals(),
                                        mod._fused_opt_state)
        if cost and cost.get("flops", 0) > 0:
            flops_per_step = float(cost["flops"])
    except Exception:
        pass
    _telemetry.set_run_info(flops_per_step=flops_per_step)

    # mxlint Layer-2 metrics of the exact benched step program (convert
    # count, donation coverage, d2h count) so BENCH_*.json tracks the
    # lint health of the hot path alongside its throughput
    mxlint_metrics = None
    try:
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "tools"))
        try:
            from diagnose_step_hlo import lower_step
        finally:
            sys.path.pop(0)
        from mxnet_tpu.analysis import hlo_passes
        mxlint_metrics = hlo_passes.metrics_from_text(
            lower_step(mod, donate=True).as_text())
    except Exception as e:
        mxlint_metrics = "failed: %s" % e

    # Layer-3 concurrency census: how many MXL6xx findings the codebase
    # carries right now, per rule (baselined debt INCLUDED — the lint
    # gate tracks growth, the census tracks the absolute count so
    # BENCH_*.json shows the debt being paid down across PRs)
    try:
        from mxnet_tpu.analysis import runner as _lint_runner
        _res = _lint_runner.run(
            ["mxnet_tpu"], baseline_path=None,
            root=os.path.dirname(os.path.abspath(__file__)),
            enabled=frozenset(["MXL601", "MXL602", "MXL603",
                               "MXL604", "MXL605", "MXL606"]))
        census = {}
        for d in _res.diags:
            census[d.rule] = census.get(d.rule, 0) + 1
        census = dict(sorted(census.items()))
        if isinstance(mxlint_metrics, dict):
            mxlint_metrics["concurrency_census"] = census
        else:
            mxlint_metrics = {"step_hlo": mxlint_metrics,
                              "concurrency_census": census}
    except Exception as e:
        census = "failed: %s" % e
        if isinstance(mxlint_metrics, dict):
            mxlint_metrics["concurrency_census"] = census

    # kernel-tier dispatch report: which ops the Pallas tier took over in
    # the traced program (counters accumulate from the module bind/trace
    # in this process), tuner hit/miss split, and the tuning-cache
    # fingerprint so BENCH_*.json lines are attributable to a specific
    # set of tuned configs (docs/tuning.md)
    kernel_tier_report = None
    try:
        from mxnet_tpu.kernels import tier as _ktier
        from mxnet_tpu.tune import cache as _tcache
        st = _ktier.stats()
        tcache = _tcache.get_default()
        kernel_tier_report = {
            "tier": st["tier"],
            "dispatch": dict(st["dispatch"]),
            "fallback": dict(st["fallback"]),
            "tuner_hits": st["tuner_hits"],
            "tuner_misses": st["tuner_misses"],
            "configs": {k: dict(v) for k, v in st["configs"].items()},
            "tuning_cache": {"entries": len(tcache.entries),
                             "version_ok": tcache.version_ok,
                             "fingerprint": tcache.fingerprint()},
        }
    except Exception as e:
        kernel_tier_report = "failed: %s" % e

    # ---- streaming data tier (BENCH_DATA=0 skips): decode+augment
    # delivery rate of the sharded streaming pipeline (mxnet_tpu/data/,
    # docs/data.md) over a make_recordio-packed synthetic set, plus the
    # headline fit's input-stall telemetry. Host-side only — no extra
    # device traffic — so it runs on CPU rounds too.
    data_pipeline = None
    if os.environ.get("BENCH_DATA", "1") == "1":
        try:
            data_pipeline = _data_leg(ctx, batch)
        except Exception as e:
            data_pipeline = "failed: %s" % e
    # input-stall attribution of the benched fit (published by fit's
    # window telemetry from host-held timers — docs/observability.md)
    input_stall_ms = stall_frac = None
    try:
        from mxnet_tpu.telemetry import registry as _treg
        g = _treg.default_registry().get("data/input_stall_ms")
        input_stall_ms = g.value() if g is not None else None
        g = _treg.default_registry().get("data/stall_frac")
        stall_frac = g.value() if g is not None else None
    except Exception:
        pass

    # ---- real-data variant (OPT-IN: BENCH_RECORDIO=1): threaded RecordIO
    # pipeline feeding the same fused module (decode+augment+H2D overlapped
    # with training). Reported as extra fields: recordio_img_s and
    # recordio_overlap (achieved / min(input-only rate, compute rate) —
    # 1.0 means the pipeline fully hides input prep). Off by default
    # because THIS environment's TPU is behind a ~1 MB/s tunnel: one 77 MB
    # f32 batch takes minutes of H2D, so any per-batch real-data feed is
    # link-bound, not pipeline-bound (a real TPU host feeds over PCIe/DMA).
    # The pipeline's own throughput/overlap is covered host-side by
    # tests/test_image_record_iter.py.
    recordio_img_s = recordio_overlap = input_only_img_s = None
    if on_tpu and os.environ.get("BENCH_RECORDIO", "0") == "1":
        from mxnet_tpu.io import ImageRecordIter
        rec = _make_rec(n_images=768, side=256)
        rit = ImageRecordIter(rec, data_shape=(3, 224, 224),
                              batch_size=batch, rand_crop=True,
                              rand_mirror=True, scale=1.0,
                              preprocess_threads=max(os.cpu_count() or 2, 2),
                              prefetch_buffer=4, ctx=ctx, seed=1)
        # input-only rate (decode+augment+device_put, no training)
        n_in = 0
        t0 = time.perf_counter()
        for b in rit:
            jax.block_until_ready(b.data[0]._data)
            n_in += batch
        np.asarray(jax.device_get(b.data[0]._data[0, 0, 0, :1]))
        input_only_img_s = n_in / (time.perf_counter() - t0)
        rit.reset()
        # overlapped: same module, fused step, real batches
        t_rec = []
        mod.fit(rit, num_epoch=3, eval_metric=None, kvstore="tpu_sync",
                optimizer="sgd",
                optimizer_params={"learning_rate": 0.05, "momentum": 0.9,
                                  "multi_precision": True},
                steps_per_dispatch=1,
                epoch_end_callback=timing_cb(t_rec))
        steps_per_epoch = 768 // batch
        dt_rec = t_rec[-1] - t_rec[0]
        recordio_img_s = batch * steps_per_epoch * (len(t_rec) - 1) / dt_rec
        recordio_overlap = recordio_img_s / min(input_only_img_s, img_s)
        rit.close()

    mfu = 0.0
    if on_tpu:
        mfu = (img_s / batch) * flops_per_step / _peak_flops(dev.device_kind)
        # A broken harness must fail loudly, not record an impossible number
        # (raise, not assert: asserts vanish under python -O).
        if not 0.0 < mfu <= 1.0:
            raise RuntimeError(
                "measured MFU %.3f is outside (0, 1] — timing harness is "
                "not measuring execution (step_ms=%.2f sync_step_ms=%.2f)"
                % (mfu, step_ms, sync_step_ms))

    out = {
        "metric": "resnet50_module_fit_img_per_sec_b%d_bf16%s"
                  % (batch, "" if on_tpu else "_CPU_FALLBACK"),
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 3),
        "mfu": round(mfu, 4),
        "step_ms": round(step_ms, 3),
        "sync_step_ms": round(sync_step_ms, 3),
        # host-side cost hidden by async dispatch: per-step latency when
        # the host waits on every step minus the pipelined per-step time.
        # Rises with tunnel RTT; ~0 means dispatch is compute-bound.
        "host_overhead_ms": round(max(0.0, sync_step_ms - step_ms), 3),
        "engine_depth": int(_flags.engine_depth),
        "device": dev.device_kind,
        "flops_per_step": flops_per_step,
    }
    if mxlint_metrics is not None:
        out["mxlint"] = mxlint_metrics
    if kernel_tier_report is not None:
        out["kernel_tier"] = kernel_tier_report
    if grouped_img_s is not None:
        out["steps_per_dispatch"] = k_disp
        out["grouped_img_s"] = round(grouped_img_s, 2)
        out["grouped_step_ms"] = round(grouped_step_ms, 3)
        if on_tpu:
            grouped_mfu = (grouped_img_s / batch) * flops_per_step \
                / _peak_flops(dev.device_kind)
            out["grouped_mfu"] = round(grouped_mfu, 4)
    if recordio_img_s is not None:
        out["recordio_img_s"] = round(recordio_img_s, 2)
        out["recordio_input_only_img_s"] = round(input_only_img_s, 2)
        out["recordio_overlap"] = round(recordio_overlap, 3)
    if data_pipeline is not None:
        out["data_pipeline"] = data_pipeline
    if input_stall_ms is not None:
        out["input_stall_ms"] = round(float(input_stall_ms), 3)
    if stall_frac is not None:
        out["stall_frac"] = round(float(stall_frac), 4)
    # the other two BASELINE.json metrics (kvstore push/pull µs, Gluon
    # LSTM tokens/sec) ride along as extra fields; BENCH_EXTRA=0 skips
    if os.environ.get("BENCH_EXTRA", "1") == "1":
        _secondary_legs(out, on_tpu)

    # end-of-run registry snapshot: the BENCH_*.json line carries the
    # same step-time/MFU/engine-depth/kernel-dispatch series an operator
    # would scrape from the Prometheus endpoint mid-run
    try:
        out["telemetry"] = _telemetry.snapshot()
    except Exception as e:
        out["telemetry"] = "failed: %s" % e

    if on_tpu:
        # persist: future runs where the TPU is unreachable re-emit this
        # (flagged stale) instead of poisoning the record with a CPU line
        try:
            with open(_LAST_TPU_PATH, "w") as f:
                json.dump(out, f)
        except OSError:
            pass
    print(json.dumps(out))


if __name__ == "__main__":
    main()
