"""Benchmark: ResNet-50 synthetic-data training throughput (img/s) on one chip.

Mirrors the reference's `train_imagenet.py --benchmark 1` measurement
(docs/faq/perf.md:228-237; BASELINE.md). vs_baseline compares against the
reference's published V100 number at the same batch size:
363.69 img/s (batch 128, MXNet 1.2 + cuDNN, docs/faq/perf.md:237).

One JSON line on stdout: {"metric", "value", "unit", "vs_baseline"}.
"""
import json
import sys
import time

import numpy as np

BASELINE_IMG_S = 363.69  # V100 ResNet-50 train, batch 128
DTYPE = "bfloat16"       # v5e MXU-native


def main():
    import jax
    import jax.numpy as jnp
    import mxnet_tpu  # noqa: F401
    from mxnet_tpu import models
    from mxnet_tpu.parallel import SPMDTrainStep, make_mesh

    try:
        devices = jax.devices("tpu")
    except RuntimeError:
        devices = []
    on_tpu = bool(devices)
    if not on_tpu:
        devices = jax.devices("cpu")[:1]
    BATCH = 128 if on_tpu else 8  # CPU fallback: smoke-size only
    mesh = make_mesh({"dp": 1}, devices=devices[:1])

    sym = models.resnet_symbol(num_classes=1000, num_layers=50)
    arg_shapes, _, aux_shapes = sym.infer_shape(data=(BATCH, 3, 224, 224))
    arg_names = sym.list_arguments()
    aux_names = sym.list_auxiliary_states()
    param_shapes = {n: tuple(s) for n, s in zip(arg_names, arg_shapes)
                    if n not in ("data", "softmax_label")}
    aux_shapes_d = {n: tuple(s) for n, s in zip(aux_names, aux_shapes)}

    step = SPMDTrainStep(sym, mesh, lr=0.05)
    step.compile(param_shapes, aux_shapes_d,
                 {"data": (BATCH, 3, 224, 224)},
                 {"softmax_label": (BATCH,)})
    params, aux, opt = step.init(param_shapes, aux_shapes_d)
    cast = lambda t: jax.tree.map(
        lambda x: x.astype(jnp.bfloat16)
        if x.dtype == jnp.float32 else x, t)
    if DTYPE == "bfloat16":
        params, aux, opt = cast(params), cast(aux), cast(opt)

    rng = np.random.RandomState(0)
    data = {"data": jnp.asarray(
        rng.randn(BATCH, 3, 224, 224), jnp.bfloat16
        if DTYPE == "bfloat16" else jnp.float32)}
    label = {"softmax_label": jnp.asarray(
        rng.randint(0, 1000, (BATCH,)), jnp.float32)}
    key = jax.random.PRNGKey(0)

    # warmup (compile)
    for _ in range(3):
        params, aux, opt, outs = step(params, aux, opt, data, label, key)
    jax.block_until_ready(outs[0])

    n_steps = 20 if on_tpu else 3
    t0 = time.perf_counter()
    for _ in range(n_steps):
        params, aux, opt, outs = step(params, aux, opt, data, label, key)
    jax.block_until_ready(outs[0])
    dt = time.perf_counter() - t0
    img_s = BATCH * n_steps / dt

    print(json.dumps({
        "metric": "resnet50_train_img_per_sec_b%d_%s%s"
                  % (BATCH, DTYPE, "" if on_tpu else "_CPU_FALLBACK"),
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 3),
    }))


if __name__ == "__main__":
    main()
